"""Query scheduler tests: admission control, deadlines, micro-batching.

Deterministic on the 8-device CPU mesh: the window tests drive the
batcher's injectable sleep hook (the leader's window ends exactly when
every expected query has enqueued), and deadline tests use the fake
monotonic clock from conftest. The real-window timing test is marked
`slow` and excluded from tier-1.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.errors import PilosaError
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.sched import (
    CLASS_BATCH,
    CLASS_INTERACTIVE,
    Deadline,
    DeadlineExceededError,
    MicroBatcher,
    QueryScheduler,
    QueueFullError,
    SchedulerConfig,
)
from pilosa_tpu.pql.parser import parse


# ------------------------------------------------------------- fixtures


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def plant(holder, n_shards=4, n_rows=8):
    """Rows 1..n_rows of field f spread over n_shards shards."""
    idx = holder.create_index_if_not_exists("i")
    idx.create_field_if_not_exists("f")
    fld = idx.field("f")
    rng = np.random.default_rng(7)
    expected = {}
    for row in range(1, n_rows + 1):
        cols = []
        for s in range(n_shards):
            local = np.flatnonzero(rng.random(2048) < 0.3)
            cols.extend(int(s * SHARD_WIDTH + c) for c in local)
        fld.import_bits([row] * len(cols), cols)
        expected[row] = len(set(cols))
    return expected


# ------------------------------------------------------------- deadline


def test_deadline_basics(fake_clock):
    d = Deadline(2.0, clock=fake_clock)
    assert not d.expired()
    assert d.remaining() == pytest.approx(2.0)
    d.check("anywhere")  # no raise
    fake_clock.advance(2.5)
    assert d.expired()
    with pytest.raises(DeadlineExceededError):
        d.check("device dispatch")


def test_deadline_from_header(fake_clock):
    d = Deadline.from_header("1.5", clock=fake_clock)
    assert d.remaining() == pytest.approx(1.5)
    # Malformed header falls back to the default instead of erroring.
    d = Deadline.from_header("bogus", default_s=3.0, clock=fake_clock)
    assert d.remaining() == pytest.approx(3.0)
    assert Deadline.from_header(None) is None
    assert Deadline.from_header("", default_s=0.0) is None
    # Non-finite values are malformed, not budgets: 'nan' must never
    # reach semaphore timeouts (it busy-spins Condition.wait), and 'inf'
    # is "no deadline" said confusingly.
    for bad in ("nan", "inf", "-inf"):
        assert Deadline.from_header(bad) is None
        d = Deadline.from_header(bad, default_s=2.0, clock=fake_clock)
        assert d.remaining() == pytest.approx(2.0)
    # Zero/negative = already-spent budget (coordinators forward
    # max(remaining, 0), so 0 must read as expired).
    assert Deadline.from_header("0", clock=fake_clock).expired()
    assert Deadline.from_header("-1", clock=fake_clock).expired()


# ------------------------------------------------------------ admission


def test_admission_reject_when_queue_full():
    sched = QueryScheduler(SchedulerConfig(
        max_queue=1, interactive_concurrency=1, retry_after=7.0))
    hold = threading.Event()
    entered = threading.Event()
    errors = []

    def occupant():
        with sched.admit(CLASS_INTERACTIVE):
            entered.set()
            hold.wait(timeout=10)

    def waiter():
        try:
            with sched.admit(CLASS_INTERACTIVE):
                pass
        except PilosaError as e:  # pragma: no cover - not expected
            errors.append(e)

    t1 = threading.Thread(target=occupant)
    t1.start()
    assert entered.wait(timeout=5)
    t2 = threading.Thread(target=waiter)
    t2.start()
    # Wait for the waiter to actually occupy the one queue slot.
    deadline = time.monotonic() + 5
    while sched.queue_depth() < 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    assert sched.queue_depth() == 1
    with pytest.raises(QueueFullError) as ei:
        with sched.admit(CLASS_INTERACTIVE):
            pass  # pragma: no cover - shed before entry
    # Retry-After is DERIVED: base x (1 + queue fullness) with +/-20%
    # jitter — full queue here, so in [7*2*0.8, 7*2*1.2], never the
    # fixed base (shed clients must not retry in lockstep).
    assert 7.0 * 2 * 0.8 <= ei.value.retry_after <= 7.0 * 2 * 1.2
    assert sched.counters["shed"] == 1
    hold.set()
    t1.join(timeout=5)
    t2.join(timeout=5)
    assert not errors
    assert sched.counters["admitted"] == 2
    assert sched.queue_depth() == 0


def test_admission_no_queue_still_admits_free_slot():
    """max_queue=0 means never WAIT — an idle class still admits."""
    sched = QueryScheduler(SchedulerConfig(max_queue=0,
                                           interactive_concurrency=1))
    with sched.admit(CLASS_INTERACTIVE):
        # Slot taken and the queue is disabled: next request sheds.
        with pytest.raises(QueueFullError):
            with sched.admit(CLASS_INTERACTIVE):
                pass  # pragma: no cover
    assert sched.counters["admitted"] == 1
    assert sched.counters["shed"] == 1


def test_admission_expired_deadline_rejected(fake_clock):
    sched = QueryScheduler(SchedulerConfig(), clock=fake_clock)
    d = Deadline(0.5, clock=fake_clock)
    fake_clock.advance(1.0)
    with pytest.raises(DeadlineExceededError):
        with sched.admit(CLASS_INTERACTIVE, d):
            pass  # pragma: no cover
    assert sched.counters["deadline_exceeded"] == 1
    assert sched.counters["admitted"] == 0


def test_admission_deadline_bounds_queued_wait():
    """A query whose whole budget elapses in the queue is rejected
    without ever running (real clock: a blocked thread can only be
    preempted by a real timeout)."""
    sched = QueryScheduler(SchedulerConfig(interactive_concurrency=1))
    hold = threading.Event()
    entered = threading.Event()

    def occupant():
        with sched.admit(CLASS_INTERACTIVE):
            entered.set()
            hold.wait(timeout=10)

    t = threading.Thread(target=occupant)
    t.start()
    assert entered.wait(timeout=5)
    with pytest.raises(DeadlineExceededError):
        with sched.admit(CLASS_INTERACTIVE, Deadline(0.05)):
            pass  # pragma: no cover
    assert sched.counters["deadline_exceeded"] == 1
    hold.set()
    t.join(timeout=5)


def test_class_limits_are_independent():
    """Import traffic saturating its class must not block interactive
    admission (and vice versa): the classes own separate slots."""
    sched = QueryScheduler(SchedulerConfig(
        interactive_concurrency=2, batch_concurrency=1, max_queue=4))
    hold = threading.Event()
    entered = threading.Event()

    def batch_occupant():
        with sched.admit(CLASS_BATCH):
            entered.set()
            hold.wait(timeout=10)

    t = threading.Thread(target=batch_occupant)
    t.start()
    assert entered.wait(timeout=5)
    # Batch class is saturated...
    snap = sched.snapshot()
    assert snap["running"][CLASS_BATCH] == 1
    # ...but interactive admits immediately, twice.
    with sched.admit(CLASS_INTERACTIVE):
        with sched.admit(CLASS_INTERACTIVE):
            snap = sched.snapshot()
            assert snap["running"][CLASS_INTERACTIVE] == 2
    hold.set()
    t.join(timeout=5)
    assert sched.counters["admitted_interactive"] == 2
    assert sched.counters["admitted_batch"] == 1


def test_pressure_is_per_class():
    """Queued + running imports must not register as interactive pressure
    (they can never coalesce with a count query, so they must not hold
    the micro-batch window open)."""
    sched = QueryScheduler(SchedulerConfig(
        interactive_concurrency=4, batch_concurrency=1, max_queue=8))
    hold = threading.Event()
    entered = threading.Event()

    def occupant():
        with sched.admit(CLASS_BATCH):
            entered.set()
            hold.wait(timeout=10)

    def waiter():
        with sched.admit(CLASS_BATCH):
            pass

    t1 = threading.Thread(target=occupant)
    t1.start()
    assert entered.wait(timeout=5)
    t2 = threading.Thread(target=waiter)
    t2.start()
    deadline = time.monotonic() + 5
    while sched.queue_depth() < 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    # One import running + one queued: zero interactive pressure.
    assert sched.pressure(CLASS_BATCH) == 2
    assert sched.pressure(CLASS_INTERACTIVE) == 0
    hold.set()
    t1.join(timeout=5)
    t2.join(timeout=5)


def test_peer_deadline_503_is_not_node_failure():
    """A peer answering 503 'deadline exceeded' ran out of REQUEST budget;
    the coordinator must not mark the healthy node unavailable."""
    from pilosa_tpu.executor import _is_node_failure
    from pilosa_tpu.server.client import ClientError

    assert not _is_node_failure(
        ClientError("POST http://n2/index/i/query: 503 "
                    '{"error": "query deadline exceeded at device dispatch"}',
                    status=503))
    assert _is_node_failure(ClientError("boom", status=503))
    assert _is_node_failure(ClientError("conn refused", status=0))
    assert not _is_node_failure(ClientError("bad query", status=400))


# ------------------------------------------------- executor integration


def test_expired_deadline_aborts_before_device_dispatch(holder, fake_clock):
    """Acceptance: an expired deadline aborts BEFORE the next device
    dispatch — the engine's launch counters stay untouched."""
    plant(holder)
    ex = Executor(holder, workers=0)
    d = Deadline(0.5, clock=fake_clock)
    fake_clock.advance(1.0)
    before = ex.engine.counters["count_dispatches"]
    with pytest.raises(DeadlineExceededError):
        ex.execute("i", "Count(Row(f=1))", opt=ExecOptions(deadline=d))
    assert ex.engine.counters["count_dispatches"] == before


def test_deadline_expires_mid_map_reduce(holder, fake_clock):
    """Per-shard gate: the budget runs out between shard maps and the
    remaining shards never run."""
    plant(holder, n_shards=3)
    ex = Executor(holder, workers=0)  # serial map, deterministic order
    d = Deadline(1.0, clock=fake_clock)
    calls = []

    def map_fn(shard):
        calls.append(shard)
        fake_clock.advance(0.6)  # each shard costs 0.6s of fake time
        return 1

    c = parse("Count(Row(f=1))").calls[0]
    with pytest.raises(DeadlineExceededError):
        ex._map_reduce("i", [0, 1, 2], c, ExecOptions(deadline=d),
                       map_fn, lambda a, b: a + b)
    # Shard 0 ran (t=0 ok), shard 1 ran (t=0.6 ok), shard 2 aborted (t=1.2).
    assert calls == [0, 1]


# -------------------------------------------------------- micro-batcher


def _coalescing_setup(holder, monkeypatch, n_queries):
    """Executor wired to a batcher whose window deterministically closes
    once all n_queries have enqueued: batch_max == n_queries, so the
    n-th arrival fills the group and wakes the leader (the production
    full-event path), with a generous window as the only fallback."""
    # Disable the result memo so a repeat query can't skip the device:
    # without the batcher each of the N queries would be its own launch,
    # making dispatches-vs-queries a true coalescing measurement.
    monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")
    ex = Executor(holder, workers=0)
    engine = ex.engine  # force creation under the env override
    batcher = MicroBatcher(
        lambda: engine,
        window=2.0, window_max=10.0, batch_max=n_queries,
        depth_fn=lambda: n_queries,
    )
    ex.batcher = batcher
    return ex, engine, batcher


def test_microbatch_coalesces_identical_counts(holder, monkeypatch):
    """Acceptance: >= 8 simultaneous identical Count queries over one
    resident stack run with FEWER engine dispatches than queries (engine
    counters) and return byte-identical results to the unbatched path."""
    expected = plant(holder)
    n = 8
    # Unbatched ground truth from a separate executor (its own engine).
    ex0 = Executor(holder, workers=0)
    truth = ex0.execute("i", "Count(Row(f=1))")[0]
    assert truth == expected[1]

    ex, engine, batcher = _coalescing_setup(holder, monkeypatch, n)
    results = [None] * n
    barrier = threading.Barrier(n)

    def client(i):
        barrier.wait(timeout=10)
        results[i] = ex.execute("i", "Count(Row(f=1))")[0]

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    before = engine.counters["count_dispatches"]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    dispatches = engine.counters["count_dispatches"] - before
    assert results == [truth] * n
    assert dispatches < n, f"no coalescing: {dispatches} dispatches for {n} queries"
    assert batcher.counters["launches"] >= 1
    assert batcher.counters["enqueued"] == n
    assert batcher.counters["coalesced"] == n - batcher.counters["launches"]


def test_microbatch_coalesces_distinct_rows_byte_identical(holder, monkeypatch):
    """Structurally identical but DISTINCT queries coalesce into one
    launch and split back per caller with exact per-query results."""
    expected = plant(holder, n_rows=8)
    n = 8
    ex0 = Executor(holder, workers=0)
    truth = {row: ex0.execute("i", f"Count(Row(f={row}))")[0]
             for row in range(1, n + 1)}
    assert truth == expected

    ex, engine, batcher = _coalescing_setup(holder, monkeypatch, n)
    results = {}
    lock = threading.Lock()
    barrier = threading.Barrier(n)

    def client(row):
        barrier.wait(timeout=10)
        r = ex.execute("i", f"Count(Row(f={row}))")[0]
        with lock:
            results[row] = r

    threads = [threading.Thread(target=client, args=(row,))
               for row in range(1, n + 1)]
    before = engine.counters["count_dispatches"]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == truth
    assert engine.counters["count_dispatches"] - before < n


def test_microbatch_group_key_respects_write_epoch(holder):
    """The group key carries the index write epoch: a write between
    batches starts a new group rather than reusing the old key."""
    plant(holder)
    ex = Executor(holder, workers=0)
    engine = ex.engine
    g1 = engine.stack_generation("i")
    holder.field("i", "f").set_bit(1, 5)
    g2 = engine.stack_generation("i")
    assert g2 > g1
    assert engine.stack_generation("missing") == -1


def test_microbatch_single_query_no_window(holder):
    """A lone query (pressure <= 1) dispatches immediately — the window
    must not add latency when there is nobody to coalesce with."""
    plant(holder)
    ex = Executor(holder, workers=0)
    waited = []
    batcher = MicroBatcher(
        lambda: ex.engine, depth_fn=lambda: 1,
        wait_window=lambda group, w: waited.append(w),
    )
    ex.batcher = batcher
    assert ex.execute("i", "Count(Row(f=1))")[0] > 0
    assert waited == []  # straight through, no window
    assert batcher.counters["enqueued"] == 0


# ------------------------------------------------------------- HTTP layer


@pytest.fixture
def server(tmp_path):
    from pilosa_tpu.server.server import Server

    s = Server(
        data_dir=str(tmp_path / "node0"), cache_flush_interval=0,
        scheduler_config=SchedulerConfig(
            max_queue=0, interactive_concurrency=1, retry_after=3.0),
    )
    s.open()
    yield s
    s.close()


def _post_query(port, body, headers=None):
    import http.client

    conn = http.client.HTTPConnection(f"localhost:{port}", timeout=30)
    try:
        conn.request("POST", "/index/i/query", body=body.encode(),
                     headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def test_http_429_with_retry_after_when_full(server):
    """Acceptance: a full queue returns 429 + Retry-After, observable in
    scheduler stats."""
    from pilosa_tpu.server.client import InternalClient

    client = InternalClient()
    host = f"localhost:{server.port}"
    client.create_index(host, "i")
    client.create_field(host, "i", "f")
    client.query(host, "i", "Set(1, f=1)")

    hold = threading.Event()
    entered = threading.Event()
    real_execute = server.executor.execute

    def slow_execute(*a, **kw):
        entered.set()
        hold.wait(timeout=10)
        return real_execute(*a, **kw)

    server.executor.execute = slow_execute
    try:
        t = threading.Thread(
            target=_post_query, args=(server.port, "Count(Row(f=1))"))
        t.start()
        assert entered.wait(timeout=10)
        # Slot busy, queue disabled -> immediate shed.
        status, headers, body = _post_query(server.port, "Count(Row(f=1))")
        assert status == 429
        # Derived Retry-After: empty queue (max_queue=0) -> base 3.0 with
        # +/-20% jitter -> [2.4, 3.6] -> ceil -> "3" or "4".
        assert headers.get("Retry-After") in ("3", "4")
        assert "queue full" in json.loads(body)["error"]
    finally:
        hold.set()
        t.join(timeout=10)
        server.executor.execute = real_execute
    snap = server.scheduler.snapshot()
    assert snap["shed"] >= 1
    assert snap["admitted"] >= 1


def test_http_deadline_header_and_stats(server):
    from pilosa_tpu.server.client import InternalClient

    client = InternalClient()
    host = f"localhost:{server.port}"
    client.create_index(host, "i")
    client.create_field(host, "i", "f")
    client.query(host, "i", "Set(1, f=1)")
    # Generous budget: normal 200.
    status, _, body = _post_query(server.port, "Count(Row(f=1))",
                                  {"X-Pilosa-Deadline": "30"})
    assert status == 200
    assert json.loads(body)["results"][0] == 1
    # Already-spent budget: 503 before any device dispatch.
    before = server.scheduler.snapshot()["deadline_exceeded"]
    status, _, body = _post_query(server.port, "Count(Row(f=1))",
                                  {"X-Pilosa-Deadline": "0"})
    assert status == 503
    assert "deadline" in json.loads(body)["error"]
    assert server.scheduler.snapshot()["deadline_exceeded"] == before + 1


def test_debug_vars_scheduler_metrics(server):
    from pilosa_tpu.server.client import InternalClient

    client = InternalClient()
    host = f"localhost:{server.port}"
    client.create_index(host, "i")
    client.create_field(host, "i", "f")
    client.query(host, "i", "Set(1, f=1)")
    client.query(host, "i", "Count(Row(f=1))")
    with urllib.request.urlopen(f"http://{host}/debug/vars") as resp:
        dv = json.load(resp)
    assert dv["scheduler"]["admitted"] >= 1
    assert "queue_depth" in dv["scheduler"]
    assert "launches" in dv["batcher"]


def test_remote_subqueries_bypass_admission(server):
    """Forwarded (remote=True) sub-queries were already admitted at the
    coordinator; re-admitting them would form cross-node slot-wait cycles
    under saturation, so they must not consume admission slots."""
    from pilosa_tpu.server.client import InternalClient

    client = InternalClient()
    host = f"localhost:{server.port}"
    client.create_index(host, "i")
    client.create_field(host, "i", "f")
    client.query(host, "i", "Set(1, f=1)")
    before = server.scheduler.counters["admitted"]
    results = server.api.query("i", "Count(Row(f=1))", remote=True)
    assert results[0] == 1
    assert server.scheduler.counters["admitted"] == before
    # Replication-forwarded imports (remote=True) bypass too.
    before_batch = server.scheduler.counters["admitted_batch"]
    status, _, _ = _post_import_remote(server.port)
    assert status == 200
    assert server.scheduler.counters["admitted_batch"] == before_batch
    # ...and so do key-mode imports forwarded to the translation primary
    # (X-Pilosa-Forwarded header; their body cannot carry remote:true).
    status, _, _ = _post_import_remote(
        server.port, body={"shard": 0, "rowIDs": [3], "columnIDs": [8]},
        headers={"X-Pilosa-Forwarded": "1"})
    assert status == 200
    assert server.scheduler.counters["admitted_batch"] == before_batch
    # Remote-path deadline expiries are still counted in scheduler stats.
    before_dl = server.scheduler.counters["deadline_exceeded"]
    expired = Deadline(0.0)
    with pytest.raises(DeadlineExceededError):
        server.api.query("i", "Count(Row(f=1))", remote=True, deadline=expired)
    assert server.scheduler.counters["deadline_exceeded"] == before_dl + 1


def _post_import_remote(port, body=None, headers=None):
    import http.client

    conn = http.client.HTTPConnection(f"localhost:{port}", timeout=30)
    try:
        payload = json.dumps(body or {"shard": 0, "rowIDs": [2],
                                      "columnIDs": [7],
                                      "remote": True}).encode()
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request("POST", "/index/i/field/f/import", body=payload,
                     headers=hdrs)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def test_imports_ride_batch_class(server):
    from pilosa_tpu.server.client import InternalClient

    client = InternalClient()
    host = f"localhost:{server.port}"
    client.create_index(host, "i")
    client.create_field(host, "i", "f")
    client.import_bits(host, "i", "f", [(1, 10), (1, 20)])
    assert server.scheduler.counters["admitted_batch"] >= 1


@pytest.mark.slow
def test_microbatch_real_window_coalesces(holder, monkeypatch):
    """Timing-sensitive twin of the deterministic coalescing test: real
    ~2ms window, real sleeps. Excluded from tier-1 (`-m 'not slow'`)."""
    plant(holder)
    monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")
    ex = Executor(holder, workers=0)
    engine = ex.engine
    ex.batcher = MicroBatcher(
        lambda: engine, window=0.002, window_max=0.02, batch_max=64,
        depth_fn=lambda: 8,
    )
    n = 8
    results = [None] * n
    barrier = threading.Barrier(n)

    def client(i):
        barrier.wait(timeout=10)
        results[i] = ex.execute("i", "Count(Row(f=1))")[0]

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    before = engine.counters["count_dispatches"]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(set(results)) == 1 and results[0] is not None
    assert engine.counters["count_dispatches"] - before < n

# ------------------------------------------------- fairness + traffic table


def _wait_until(cond, timeout=5.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


def test_admit_fifo_no_fast_path_barging():
    """Release order is strict FIFO: once waiters are parked, a freed
    slot goes to the HEAD of the queue, and a late arrival parks behind
    everyone instead of barging through the fast path."""
    sched = QueryScheduler(SchedulerConfig(
        max_queue=8, interactive_concurrency=1))
    hold = threading.Event()
    entered = threading.Event()
    order = []
    threads = []

    def occupant():
        with sched.admit(CLASS_INTERACTIVE):
            entered.set()
            assert hold.wait(timeout=10)

    def client(name):
        with sched.admit(CLASS_INTERACTIVE):
            order.append(name)

    t0 = threading.Thread(target=occupant)
    t0.start()
    threads.append(t0)
    assert entered.wait(timeout=10)
    # Park w0..w2 one at a time so their queue positions are known.
    for i in range(3):
        t = threading.Thread(target=client, args=(f"w{i}",))
        t.start()
        threads.append(t)
        assert _wait_until(lambda i=i: sched.queue_depth() == i + 1)
    # A late arrival while the slot is STILL held and waiters are parked
    # must join the tail — the fast path is closed to it.
    late = threading.Thread(target=client, args=("late",))
    late.start()
    threads.append(late)
    assert _wait_until(lambda: sched.queue_depth() == 4)
    hold.set()
    for t in threads:
        t.join(timeout=10)
    assert order == ["w0", "w1", "w2", "late"]


def test_note_index_recency_eviction_at_bound():
    """The traffic table holds exactly 1024 indexes and evicts by
    RECENCY: re-touching an old index saves it; the least recently
    touched entry goes when a new one arrives at the bound."""
    sched = QueryScheduler(SchedulerConfig())
    for i in range(1024):
        sched.note_index(f"idx-{i}")
    assert len(sched.index_traffic()) == 1024
    # Refresh idx-0's recency, then push one more index over the bound:
    # idx-1 (now the least recently touched) is the victim, not idx-0.
    sched.note_index("idx-0")
    sched.note_index("idx-new")
    t = sched.index_traffic()
    assert len(t) == 1024
    assert t["idx-0"] == 2
    assert t["idx-new"] == 1
    assert "idx-1" not in t
    assert "idx-2" in t


def test_snapshot_trims_index_traffic_to_top_n():
    """snapshot() carries only the top-32 busiest indexes (plus the full
    table size) so /debug/vars stops growing with schema churn, while
    index_traffic() keeps the complete table for prefetch/autoscale."""
    sched = QueryScheduler(SchedulerConfig())
    for i in range(40):
        for _ in range(i + 1):
            sched.note_index(f"idx-{i}")
    snap = sched.snapshot()
    top = snap["index_traffic"]
    assert len(top) == sched.SNAPSHOT_TRAFFIC_TOP == 32
    # The 32 busiest are idx-8..idx-39 (touch counts 9..40).
    assert set(top) == {f"idx-{i}" for i in range(8, 40)}
    assert top["idx-39"] == 40
    assert snap["index_traffic_total"] == 40
    assert len(sched.index_traffic()) == 40


def test_derived_retry_after_scales_with_fullness_and_clamps_jitter():
    """Retry-After grows with queue fullness and jitters around the
    base; a percent-spelled jitter knob (20 instead of 0.2) clamps to
    the fraction 1.0 instead of producing negative waits."""
    import random as _random

    sched = QueryScheduler(
        SchedulerConfig(max_queue=4, retry_after=10.0, retry_jitter=0.2),
        rng=_random.Random(7))
    with sched._lock:
        sched._waiting_by[CLASS_BATCH] = 0
        empty = sched._derived_retry_after(CLASS_BATCH)
        sched._waiting_by[CLASS_BATCH] = 4
        full = sched._derived_retry_after(CLASS_BATCH)
        sched._waiting_by[CLASS_BATCH] = 0
    assert 10.0 * 0.8 <= empty <= 10.0 * 1.2
    assert 20.0 * 0.8 <= full <= 20.0 * 1.2
    # Percent-vs-fraction: jitter=20 clamps to 1.0 -> worst case doubles
    # the scaled base, never goes negative (floor is 0.05s).
    wild = QueryScheduler(
        SchedulerConfig(max_queue=4, retry_after=10.0, retry_jitter=20.0),
        rng=_random.Random(7))
    for _ in range(50):
        with wild._lock:
            r = wild._derived_retry_after(CLASS_INTERACTIVE)
        assert 0.05 <= r <= 20.0
