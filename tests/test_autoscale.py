"""Autoscaler tests: hysteresis, bounds, checkpoint, and full revert.

The decision logic runs against a stub server on the fake clock (every
sample is hand-fed, every gate asserted by counter); the cluster-level
tests drive a REAL coordinator + standby through the scale-out/scale-in
loop and the abort-mid-migration reverse migration.
"""

import json
import logging
import os
import time

import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.cluster.autoscale import (
    STATE_FILE,
    AutoscaleConfig,
    AutoscaleController,
    _hist_p99,
)
from pilosa_tpu.cluster.node import Node
from pilosa_tpu.cluster.rebalance import RebalanceConfig
from pilosa_tpu.obs import ObsConfig, TraceRecorder
from pilosa_tpu.sched import QueryScheduler, SchedulerConfig
from pilosa_tpu.stats import Histogram


# ------------------------------------------------------------------ config


def test_autoscale_config_validation():
    AutoscaleConfig().validate()  # defaults legal (and disabled: interval 0)
    for bad in (
        AutoscaleConfig(interval=-1),
        AutoscaleConfig(window=0),
        AutoscaleConfig(scale_out_qps=0),
        AutoscaleConfig(scale_in_qps=200.0),  # >= scale-out-qps
        AutoscaleConfig(scale_in_qps=-1),
        AutoscaleConfig(p99_ms=-1),
        AutoscaleConfig(cooldown=-1),
        AutoscaleConfig(min_nodes=0),
        AutoscaleConfig(min_nodes=3, max_nodes=2),
    ):
        with pytest.raises(ValueError):
            bad.validate()


def test_standby_uris_parsing():
    cfg = AutoscaleConfig(standby=" h1:1, h2:2 ,,h3:3 ")
    assert cfg.standby_uris() == ["h1:1", "h2:2", "h3:3"]
    assert AutoscaleConfig().standby_uris() == []


# ---------------------------------------------------------------- _hist_p99


def test_hist_p99_from_log_buckets():
    h = Histogram()
    for _ in range(99):
        h.observe(1.0)
    h.observe(1000.0)
    p99 = _hist_p99(h.snapshot())
    # The smallest bucket bound covering 99% of samples: the 1.0ms mass,
    # not the single outlier.
    assert 1.0 <= p99 <= 2.0
    # Empty histogram -> 0; all-overflow mass falls back to observed max.
    assert _hist_p99({"count": 0, "buckets": {}}) == 0.0
    assert _hist_p99(
        {"count": 10, "max": 123.0, "buckets": {"+Inf": 10}}) == 123.0


# ------------------------------------------------------------ decision unit


class _StubCluster:
    def __init__(self):
        self.nodes = [Node(id="n0", uri="localhost:1")]
        self.coord = True

    def is_coordinator(self):
        return self.coord

    def node_by_id(self, node_id):
        return next((n for n in self.nodes if n.id == node_id), None)


class _StubCoordinator:
    def __init__(self):
        self.job = None
        self.revert_on_abort = False


class _StubClient:
    def __init__(self):
        self.statuses = {}

    def status(self, uri):
        st = self.statuses.get(uri)
        if st is None:
            raise OSError(f"standby {uri} unreachable")
        return st


class _StubServer:
    """The slice of Server the controller touches, nothing else."""

    def __init__(self, tmp_path, sample_rate=0.0):
        self.data_dir = str(tmp_path)
        self.logger = logging.getLogger("test-autoscale")
        self.scheduler = QueryScheduler(SchedulerConfig())
        self.trace_recorder = TraceRecorder(ObsConfig(sample_rate=sample_rate))
        self.cluster = _StubCluster()
        self.rebalance_config = RebalanceConfig()
        self.rebalance_coordinator = _StubCoordinator()
        self.client = _StubClient()
        self.joins = []
        self.leaves = []
        self.join_makes_job = False

    def handle_node_join(self, node):
        self.joins.append(node.id)
        self.cluster.nodes.append(node)
        if self.join_makes_job:
            self.rebalance_coordinator.job = object()

    def handle_node_leave(self, node_id):
        self.leaves.append(node_id)
        self.cluster.nodes = [
            n for n in self.cluster.nodes if n.id != node_id]


def ctrl(server, fake_clock, **kw):
    kw.setdefault("interval", 1.0)
    kw.setdefault("window", 3)
    kw.setdefault("scale_out_qps", 100.0)
    kw.setdefault("scale_in_qps", 10.0)
    kw.setdefault("cooldown", 60.0)
    kw.setdefault("standby", "localhost:9")
    return AutoscaleController(
        server, AutoscaleConfig(**kw), clock=fake_clock)


def drive(server, c, fake_clock, qps, steps=1):
    """Advance one second per step, planting `qps` queries of traffic."""
    out = []
    for _ in range(steps):
        fake_clock.advance(1.0)
        for _ in range(int(qps)):
            server.scheduler.note_index("i")
        out.append(c.step())
    return out


def test_first_step_seeds_baseline(tmp_path, fake_clock):
    s = _StubServer(tmp_path)
    c = ctrl(s, fake_clock)
    assert c.step() == "seeding"
    assert c.counters["samples"] == 0
    assert c.counters["steps"] == 1


def test_hysteresis_needs_full_agreeing_window(tmp_path, fake_clock):
    s = _StubServer(tmp_path)
    s.client.statuses["localhost:9"] = {"localID": "s1"}
    c = ctrl(s, fake_clock)
    c.step()  # seed
    # Two high samples: window of 3 not yet full -> hold, no action.
    assert drive(s, c, fake_clock, 150, 2) == ["hold", "hold"]
    assert s.joins == []
    # A mixed window (high, high, low) must also hold: one cool sample
    # resets the excursion, that's the whole point of hysteresis.
    assert drive(s, c, fake_clock, 5, 1) == ["hold"]
    assert drive(s, c, fake_clock, 150, 2) == ["hold", "hold"]
    assert s.joins == []
    # The third consecutive high sample acts.
    assert drive(s, c, fake_clock, 150, 1) == ["out"]
    assert s.joins == ["s1"]
    assert c.counters["scale_out"] == 1


def test_scale_out_checkpoint_and_revert_arming(tmp_path, fake_clock):
    s = _StubServer(tmp_path)
    s.client.statuses["localhost:9"] = {"localID": "s1"}
    s.join_makes_job = True
    c = ctrl(s, fake_clock)
    c.step()
    drive(s, c, fake_clock, 150, 3)
    # The standby's REPORTED identity was admitted (never an invented id),
    # the revert contract is armed while the join's job is in flight, and
    # the added-node list survives restarts via the checkpoint.
    assert s.joins == ["s1"]
    assert s.rebalance_coordinator.revert_on_abort is True
    with open(os.path.join(s.data_dir, STATE_FILE)) as f:
        assert json.load(f)["added"] == ["s1"]
    # The window was consumed: the NEXT action needs a fresh mandate.
    assert c.snapshot()["window"] == []


def test_join_without_job_disarms_revert(tmp_path, fake_clock):
    # An empty-holder join is a plain status broadcast — no job to guard;
    # leaving the flag armed would hijack a later operator abort.
    s = _StubServer(tmp_path)
    s.client.statuses["localhost:9"] = {"localID": "s1"}
    s.join_makes_job = False
    c = ctrl(s, fake_clock)
    c.step()
    drive(s, c, fake_clock, 150, 3)
    assert s.joins == ["s1"]
    assert s.rebalance_coordinator.revert_on_abort is False


def test_inflight_job_and_cooldown_block_actions(tmp_path, fake_clock):
    s = _StubServer(tmp_path)
    s.client.statuses["localhost:9"] = {"localID": "s1"}
    s.join_makes_job = True
    c = ctrl(s, fake_clock, cooldown=60.0, max_nodes=9)
    c.step()
    drive(s, c, fake_clock, 150, 3)  # acts: job now in flight
    assert c.counters["scale_out"] == 1
    # Sustained load continues, but the running rebalance blocks.
    assert drive(s, c, fake_clock, 150, 3)[-1] == "skipped-rebalancing"
    # Job completes; the cooldown still holds the next action.
    s.rebalance_coordinator.job = None
    assert drive(s, c, fake_clock, 150, 1) == ["skipped-cooldown"]
    assert c.counters["skipped_cooldown"] == 1
    # Past the cooldown the controller may act again — but the standby
    # pool is exhausted (s1 already a member) -> bounds skip, not a join.
    # (Four steps: the long idle gap dilutes the first sample's qps, so a
    # fresh 3-high window needs three more.)
    fake_clock.advance(61.0)
    drive(s, c, fake_clock, 150, 4)
    assert c.counters["skipped_bounds"] >= 1
    assert s.joins == ["s1"]  # still just the one


def test_membership_bounds(tmp_path, fake_clock):
    # max-nodes stops scale-out before the standby is even probed.
    s = _StubServer(tmp_path)
    s.client.statuses["localhost:9"] = {"localID": "s1"}
    c = ctrl(s, fake_clock, max_nodes=1, cooldown=0.0)
    c.step()
    drive(s, c, fake_clock, 150, 3)
    assert s.joins == [] and c.counters["skipped_bounds"] == 1
    # min-nodes stops scale-in at the floor.
    s2 = _StubServer(tmp_path / "b")
    c2 = ctrl(s2, fake_clock, min_nodes=1, cooldown=0.0)
    c2.step()
    drive(s2, c2, fake_clock, 0, 3)
    assert s2.leaves == [] and c2.counters["skipped_bounds"] == 1


def test_scale_in_only_takes_back_added_nodes(tmp_path, fake_clock):
    s = _StubServer(tmp_path)
    # Two-node cluster the OPERATOR built: sustained idle must not
    # shrink it — the controller only removes nodes it added.
    s.cluster.nodes.append(Node(id="op1", uri="localhost:2"))
    c = ctrl(s, fake_clock, cooldown=0.0)
    c.step()
    assert drive(s, c, fake_clock, 0, 3)[-1] == "hold"
    assert s.leaves == [] and c.counters["skipped_bounds"] == 1
    # After its own scale-out, the controller takes that node back.
    s.client.statuses["localhost:9"] = {"localID": "s1"}
    drive(s, c, fake_clock, 150, 3)
    assert s.joins == ["s1"]
    assert drive(s, c, fake_clock, 0, 3)[-1] == "in"
    assert s.leaves == ["s1"]
    with open(os.path.join(s.data_dir, STATE_FILE)) as f:
        assert json.load(f)["added"] == []


def test_non_coordinator_samples_but_never_acts(tmp_path, fake_clock):
    s = _StubServer(tmp_path)
    s.client.statuses["localhost:9"] = {"localID": "s1"}
    s.cluster.coord = False
    c = ctrl(s, fake_clock)
    c.step()
    assert drive(s, c, fake_clock, 150, 3) == ["not-coordinator"] * 3
    assert s.joins == [] and c.counters["samples"] == 3
    # Failover promotion: the window is already warm, the promoted
    # coordinator can act on its very next step.
    s.cluster.coord = True
    assert drive(s, c, fake_clock, 150, 1) == ["out"]
    assert s.joins == ["s1"]


def test_offline_rebalance_never_acts(tmp_path, fake_clock):
    # The revert contract only exists on the online rebalance path; the
    # stop-the-world resize must never be autoscale-triggered.
    s = _StubServer(tmp_path)
    s.client.statuses["localhost:9"] = {"localID": "s1"}
    s.rebalance_config = RebalanceConfig(online=False)
    c = ctrl(s, fake_clock)
    c.step()
    assert drive(s, c, fake_clock, 150, 3) == ["offline-rebalance"] * 3
    assert s.joins == []


def test_p99_trigger_scales_out_at_low_qps(tmp_path, fake_clock):
    # A few expensive tenants can saturate devices at low qps: the
    # latency watermark counts as sustained-high on its own.
    s = _StubServer(tmp_path, sample_rate=1.0)
    s.client.statuses["localhost:9"] = {"localID": "s1"}
    for _ in range(20):
        t = s.trace_recorder.maybe_start(index="i", pql="q")
        t.record("device.dispatch", 400.0)
        s.trace_recorder.finish(t)
    c = ctrl(s, fake_clock, p99_ms=50.0, scale_out_qps=1e9)
    c.step()
    assert drive(s, c, fake_clock, 2, 3)[-1] == "out"
    assert s.joins == ["s1"]


def test_checkpoint_reload_and_corruption(tmp_path, fake_clock):
    with open(os.path.join(str(tmp_path), STATE_FILE), "w") as f:
        json.dump({"added": ["a", "b"]}, f)
    c = ctrl(_StubServer(tmp_path), fake_clock)
    assert c.snapshot()["added_nodes"] == ["a", "b"]
    # A corrupt checkpoint logs and starts empty — never bricks startup.
    with open(os.path.join(str(tmp_path), STATE_FILE), "w") as f:
        f.write("{nope")
    c2 = ctrl(_StubServer(tmp_path), fake_clock)
    assert c2.snapshot()["added_nodes"] == []


def test_step_is_single_flight(tmp_path, fake_clock):
    c = ctrl(_StubServer(tmp_path), fake_clock)
    assert c._flight.acquire(blocking=False)
    try:
        assert c.step() == "skipped-inflight"
        assert c.counters["skipped_inflight"] == 1
    finally:
        c._flight.release()


def test_autoscale_step_failpoint(tmp_path, fake_clock):
    c = ctrl(_StubServer(tmp_path), fake_clock)
    failpoints.configure("autoscale-step", "error", count=1,
                         message="injected controller fault")
    try:
        with pytest.raises(failpoints.InjectedFault):
            c.step()
    finally:
        failpoints.reset()
    assert c.step() == "seeding"  # flight lock released on the error path


# --------------------------------------------------------- cluster-level


from pilosa_tpu.constants import SHARD_WIDTH  # noqa: E402
from pilosa_tpu.server.client import InternalClient  # noqa: E402
from pilosa_tpu.server.server import Server  # noqa: E402

N_SHARDS = 4
INDEX = "asc"


def free_port():
    import socket

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def scale_ports(min_gains=1):
    """A (coordinator, standby) port pair whose 1->2 placement actually
    hands the standby >= min_gains shards (node ids derive from random
    ports; an arbitrary pair can be a no-op placement)."""
    from pilosa_tpu.cluster.hash import partition as partition_of

    for _ in range(64):
        ports = [free_port(), free_port()]
        hosts = [f"localhost:{p}" for p in ports]
        ordered = sorted(hosts)
        gains = [sh for sh in range(N_SHARDS)
                 if ordered[partition_of(INDEX, sh, 256) % 2] == hosts[1]]
        if min_gains <= len(gains) < N_SHARDS:
            return ports, hosts, gains
    raise RuntimeError("could not find a scaling port pair")


def make_server(tmp_path, name, port, **kw):
    from pilosa_tpu.cluster.hash import ModHasher
    from pilosa_tpu.cluster.health import ResilienceConfig

    kw.setdefault("cache_flush_interval", 0)
    kw.setdefault("member_monitor_interval", 0)
    kw.setdefault("anti_entropy_interval", 0)
    kw.setdefault("executor_workers", 0)
    kw.setdefault("hasher", ModHasher())
    kw.setdefault("rebalance_config", RebalanceConfig(
        catchup_threshold_bytes=256, max_catchup_rounds=8,
        cutover_pause_max=2.0,
    ))
    kw.setdefault("resilience_config", ResilienceConfig(
        breaker_backoff=0.1, breaker_backoff_max=0.5,
        retry_budget=100.0, retry_refill=1.0,
    ))
    s = Server(data_dir=str(tmp_path / name), port=port, **kw)
    s.open()
    return s


def wait_for(cond, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.03)
    return False


def load_base(client, h0):
    client.ensure_index(h0, INDEX)
    client.ensure_field(h0, INDEX, "f")
    time.sleep(0.05)
    cols = [sh * SHARD_WIDTH + 7 for sh in range(N_SHARDS)]
    for col in cols:
        client.query(h0, INDEX, f"Set({col}, f=1)")
    assert client.query(
        h0, INDEX, "Count(Row(f=1))")["results"][0] == N_SHARDS
    return cols


def pump_traffic(server, n=200):
    for _ in range(n):
        server.scheduler.note_index(INDEX)


@pytest.mark.chaos
def test_cluster_scale_out_then_in(tmp_path):
    """Load-driven membership, no operator action: sustained traffic
    admits the standby through the real coordinator join path; sustained
    idle takes exactly that node back. Data serves throughout."""
    ports, hosts, gains = scale_ports()
    h0srv = make_server(tmp_path, "n0", ports[0], cluster_hosts=[hosts[0]])
    standby = make_server(tmp_path, "s1", ports[1],
                          cluster_hosts=[hosts[1]], is_coordinator=True)
    servers = [h0srv, standby]
    client = InternalClient(timeout=10.0)
    h0 = h0srv.node.uri
    try:
        load_base(client, h0)
        c = AutoscaleController(h0srv, AutoscaleConfig(
            interval=1.0, window=1, scale_out_qps=5.0, scale_in_qps=1.0,
            cooldown=0.0, standby=hosts[1],
        ))
        assert c.step() == "seeding"
        time.sleep(0.05)
        pump_traffic(h0srv)
        assert c.step() == "out"
        stats = h0srv.rebalance_stats.counters
        assert wait_for(
            lambda: stats["jobs_completed"] >= 1
            and len(h0srv.cluster.nodes) == 2
            and h0srv.cluster.next_nodes is None
        ), "autoscale join did not complete"
        # Revert arming is transient: a completed job clears it.
        assert h0srv.rebalance_coordinator.revert_on_abort is False
        assert client.query(
            h0, INDEX, "Count(Row(f=1))")["results"][0] == N_SHARDS
        for sh in gains:
            assert standby.holder.fragment(
                INDEX, "f", "standard", sh) is not None
        with open(os.path.join(h0srv.data_dir, STATE_FILE)) as f:
            assert json.load(f)["added"] == [standby.node.id]

        # Sustained idle: the controller removes ONLY the node it added.
        # (The verification queries above count as traffic; poll until
        # the rate decays under the low watermark.)
        assert wait_for(lambda: c.step() == "in", timeout=10), \
            "sustained idle did not trigger scale-in"
        assert wait_for(
            lambda: stats["jobs_completed"] >= 2
            and len(h0srv.cluster.nodes) == 1
            and h0srv.cluster.next_nodes is None
        ), "autoscale leave did not complete"
        assert client.query(
            h0, INDEX, "Count(Row(f=1))")["results"][0] == N_SHARDS
        with open(os.path.join(h0srv.data_dir, STATE_FILE)) as f:
            assert json.load(f)["added"] == []
        assert c.counters["scale_out"] == 1 and c.counters["scale_in"] == 1
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


@pytest.mark.chaos
def test_abort_mid_migration_fully_reverts(tmp_path):
    """THE autoscale revert test: an autoscale-started join aborted
    after >= 1 shard committed escalates (revert_on_abort) into the
    reverse migration — routing restored with zero mixed state, zero
    frozen fragments, all acked data served by the prior owner."""
    ports, hosts, gains = scale_ports(min_gains=2)
    throttled = RebalanceConfig(
        catchup_threshold_bytes=256, max_catchup_rounds=8,
        cutover_pause_max=2.0, max_bytes_per_sec=8192,
    )
    h0srv = make_server(tmp_path, "n0", ports[0], cluster_hosts=[hosts[0]],
                        rebalance_config=throttled)
    standby = make_server(tmp_path, "s1", ports[1],
                          cluster_hosts=[hosts[1]], is_coordinator=True,
                          rebalance_config=throttled)
    servers = [h0srv, standby]
    client = InternalClient(timeout=10.0)
    h0 = h0srv.node.uri
    try:
        load_base(client, h0)
        # Fatten the LAST gaining shard so it streams for seconds under
        # the byte throttle while the first commits quickly — a wide,
        # deterministic abort window between the two cutovers.
        fat = gains[-1]
        offs = [o for o in range(0, 200000, 10) if o != 7]
        client.import_bits(
            h0, INDEX, "f",
            [(1, fat * SHARD_WIDTH + o) for o in offs])
        acked = N_SHARDS + len(offs)
        assert client.query(
            h0, INDEX, "Count(Row(f=1))")["results"][0] == acked

        c = AutoscaleController(h0srv, AutoscaleConfig(
            interval=1.0, window=1, scale_out_qps=5.0, scale_in_qps=1.0,
            cooldown=0.0, standby=hosts[1],
        ))
        c.step()
        time.sleep(0.05)
        pump_traffic(h0srv)
        # Deterministic abort window: the per-instruction byte throttle is
        # SHARED across the concurrent shard streams, so both can drain
        # together and their cutovers cluster at job end — polling for
        # committed >= 1 then races a millisecond window. A count=1
        # latency delays exactly ONE shard's catch-up pull: the other
        # commits >= 1.5s before the job can complete, whatever the
        # stream interleaving.
        failpoints.configure("migrate-delta", "latency", count=1,
                             arg=1500.0)
        assert c.step() == "out"
        coord = h0srv.rebalance_coordinator
        assert coord is not None and coord.revert_on_abort is True

        def committed_one():
            job = coord.job
            return (job is not None and not job.revert
                    and len(job.committed) >= 1)

        # Generous timeout: under full-suite CPU load the throttled fat
        # shard stream can crawl, but the tiny shards always commit first.
        assert wait_for(committed_one, timeout=90.0), \
            "no shard committed before the abort window"
        # Chaos: abort mid-migration. No revert=True needed — the
        # autoscaler's armed contract escalates the plain abort.
        coord.abort("chaos: injected mid-migration abort")
        stats = h0srv.rebalance_stats.counters
        assert wait_for(
            lambda: stats.get("jobs_reverted", 0) >= 1
            and coord.job is None
        ), "reverse migration did not complete"
        # Routing fully restored: prior membership, no overrides, no
        # mixed per-shard state, flag disarmed.
        assert len(h0srv.cluster.nodes) == 1
        assert h0srv.cluster.next_nodes is None
        assert h0srv.cluster.migrated == set()
        assert coord.revert_on_abort is False
        # Every shard is served by the prior owner again...
        for sh in range(N_SHARDS):
            owners = [n.id for n in h0srv.cluster.shard_nodes(INDEX, sh)]
            assert owners == [h0srv.node.id]
        # ...with zero lost acked writes, byte-for-byte.
        assert client.query(
            h0, INDEX, "Count(Row(f=1))")["results"][0] == acked
        # And nothing stayed frozen: new writes land immediately.
        client.query(h0, INDEX, f"Set({gains[0] * SHARD_WIDTH + 99}, f=1)")
        assert client.query(
            h0, INDEX, "Count(Row(f=1))")["results"][0] == acked + 1
    finally:
        failpoints.reset()
        for s in servers:
            try:
                s.close()
            except Exception:
                pass
