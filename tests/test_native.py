"""Native C++ kernel tests: build via make, compare against numpy."""

import numpy as np
import pytest

from pilosa_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)

RNG = np.random.default_rng(11)


def sorted_u16(n, span=65536):
    return np.unique(RNG.integers(0, span, n)).astype(np.uint16)


def test_pack_unpack():
    cols = np.unique(RNG.integers(0, 1 << 16, 5000)).astype(np.uint32)
    words = native.pack_bits(cols, (1 << 16) // 32)
    ref = np.zeros((1 << 16) // 32, dtype=np.uint32)
    np.bitwise_or.at(ref, cols >> 5, np.uint32(1) << (cols & np.uint32(31)))
    assert np.array_equal(words, ref)
    assert np.array_equal(native.unpack_bits(words), cols.astype(np.uint64))


def test_container_ops_vs_numpy():
    a, b = sorted_u16(3000), sorted_u16(3000)
    assert native.intersection_count_u16(a, b) == len(
        np.intersect1d(a, b, assume_unique=True)
    )
    assert np.array_equal(native.intersect_u16(a, b), np.intersect1d(a, b))
    assert np.array_equal(native.union_u16(a, b), np.union1d(a, b))
    assert np.array_equal(
        native.difference_u16(a, b), np.setdiff1d(a, b, assume_unique=True)
    )
    assert np.array_equal(native.xor_u16(a, b), np.setxor1d(a, b))


def test_empty_inputs():
    e = np.empty(0, dtype=np.uint16)
    a = sorted_u16(100)
    assert native.intersection_count_u16(a, e) == 0
    assert len(native.intersect_u16(e, e)) == 0
    assert np.array_equal(native.union_u16(a, e), a)


def test_bitmap_uses_native():
    from pilosa_tpu.storage.bitmap import Bitmap

    xs, ys = set(range(0, 100000, 3)), set(range(0, 100000, 7))
    a, b = Bitmap(sorted(xs)), Bitmap(sorted(ys))
    assert set(a.intersect(b).slice().tolist()) == xs & ys
    assert a.intersection_count(b) == len(xs & ys)
