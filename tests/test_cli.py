"""CLI + config tests (model: reference cmd/*_test.go, ctl import/export
tests against an in-process node)."""

import json
import os

import pytest

from pilosa_tpu.cli import main
from pilosa_tpu.config import Config
from pilosa_tpu.server.server import Server


def test_config_precedence(tmp_path, monkeypatch):
    cfg_file = tmp_path / "cfg.toml"
    cfg_file.write_text(
        'data-dir = "/from/file"\nbind = "localhost:1111"\n'
        "[cluster]\nreplicas = 2\n"
    )
    cfg = Config.load(str(cfg_file))
    assert cfg.data_dir == "/from/file"
    assert cfg.cluster.replicas == 2
    # Env beats file.
    monkeypatch.setenv("PILOSA_TPU_DATA_DIR", "/from/env")
    cfg = Config.load(str(cfg_file))
    assert cfg.data_dir == "/from/env"
    # Flags beat env.
    cfg = Config.load(str(cfg_file), {"data_dir": "/from/flag"})
    assert cfg.data_dir == "/from/flag"


def test_gossip_config_surface(tmp_path, monkeypatch):
    """Reference server/config.go:121-131 gossip{} knobs: TOML + env + flag
    precedence, and build_server wiring into the heartbeat monitor."""
    cfg_file = tmp_path / "cfg.toml"
    cfg_file.write_text(
        "[gossip]\nprobe-interval = 7.5\nprobe-timeout = 1.5\n"
        'key = "/from/file.key"\n'
    )
    cfg = Config.load(str(cfg_file))
    assert cfg.gossip.probe_interval == 7.5
    assert cfg.gossip.probe_timeout == 1.5
    assert cfg.gossip.key == "/from/file.key"
    monkeypatch.setenv("PILOSA_TPU_GOSSIP_PROBE_INTERVAL", "3.0")
    cfg = Config.load(str(cfg_file))
    assert cfg.gossip.probe_interval == 3.0
    cfg = Config.load(str(cfg_file), {"gossip_probe_interval": 9.0})
    assert cfg.gossip.probe_interval == 9.0
    # Round-trips through generate-config output.
    p = tmp_path / "rt.toml"
    p.write_text(cfg.to_toml())
    rt = Config.load(str(p))
    # (env still set, so compare the file-only fields)
    assert rt.gossip.probe_timeout == 1.5
    assert rt.gossip.key == "/from/file.key"

    keyfile = tmp_path / "secret.key"
    keyfile.write_text("s3cret\n")
    cfg = Config()
    cfg.data_dir = str(tmp_path / "d")
    cfg.bind = "localhost:0"
    cfg.gossip.probe_interval = 0  # don't spawn the monitor in tests
    cfg.gossip.probe_timeout = 0.5
    cfg.gossip.key = str(keyfile)
    s = cfg.build_server(executor_workers=0, cache_flush_interval=0)
    try:
        assert s.internal_key == "s3cret"
        assert s._probe_client.timeout == 0.5
        assert s._probe_client.key == "s3cret"
        assert s.member_monitor_interval == 0
    finally:
        pass  # never opened


def test_resilience_config_surface(tmp_path, monkeypatch):
    """[resilience] + gossip.probe-failures knobs: TOML + env + flag
    precedence, to_toml round-trip, and build_server wiring into the
    cluster health registry / member monitor."""
    cfg_file = tmp_path / "cfg.toml"
    cfg_file.write_text(
        "[resilience]\nbreaker-failures = 2\nretry-budget = 5.0\n"
        "hedge-max-fraction = 0.1\nbreaker-backoff = 0.5\n"
        "[gossip]\nprobe-failures = 5\n"
    )
    cfg = Config.load(str(cfg_file))
    assert cfg.resilience.breaker_failures == 2
    assert cfg.resilience.retry_budget == 5.0
    assert cfg.resilience.hedge_max_fraction == 0.1
    assert cfg.gossip.probe_failures == 5
    monkeypatch.setenv("PILOSA_TPU_RESILIENCE_RETRY_BUDGET", "7")
    cfg = Config.load(str(cfg_file))
    assert cfg.resilience.retry_budget == 7.0
    cfg = Config.load(str(cfg_file), {"resilience_retry_budget": 9.0})
    assert cfg.resilience.retry_budget == 9.0
    # Round-trips through generate-config output (env cleared: env beats
    # file, so the lingering override would mask the file's value).
    monkeypatch.delenv("PILOSA_TPU_RESILIENCE_RETRY_BUDGET")
    p = tmp_path / "rt.toml"
    p.write_text(cfg.to_toml())
    rt = Config.load(str(p))
    assert rt.resilience.retry_budget == 9.0
    assert rt.resilience.breaker_failures == 2
    assert rt.gossip.probe_failures == 5

    cfg.data_dir = str(tmp_path / "d")
    cfg.bind = "localhost:0"
    cfg.gossip.probe_interval = 0
    s = cfg.build_server(executor_workers=0, cache_flush_interval=0)
    assert s.member_probe_failures == 5
    assert s.cluster.health.config.retry_budget == 9.0
    assert s.cluster.health.config.breaker_failures == 2

    # Invalid knobs are rejected at build time, not at first failure.
    cfg.resilience.hedge_max_fraction = 2.0
    with pytest.raises(ValueError):
        cfg.build_server(executor_workers=0)


def test_internal_key_enforced(tmp_path):
    """A node with a cluster key refuses unauthenticated /internal/* (the
    memberlist-encryption analog): wrong key -> 403, right key -> 200,
    public routes stay open."""
    import urllib.error
    import urllib.request

    from pilosa_tpu.server.client import ClientError, InternalClient

    keyfile = tmp_path / "k"
    keyfile.write_text("hunter2")
    s = Server(
        data_dir=str(tmp_path / "node"),
        port=0,
        cache_flush_interval=0,
        member_monitor_interval=0,
        executor_workers=0,
        internal_key_path=str(keyfile),
    )
    s.open()
    try:
        h = f"localhost:{s.port}"
        # Unkeyed client: public route OK, internal route 403.
        anon = InternalClient()
        assert anon.status(h)["state"] is not None
        with pytest.raises(ClientError) as ei:
            anon.shards_max(h)
        assert ei.value.status == 403
        # Wrong key: still 403.
        wrong = InternalClient(key="nope")
        with pytest.raises(ClientError) as ei:
            wrong.shards_max(h)
        assert ei.value.status == 403
        # Right key: internal plane open.
        good = InternalClient(key="hunter2")
        assert good.shards_max(h) is not None
        # Non-ASCII header bytes must 403, not crash the connection
        # (http.server hands headers to the gate as latin-1 str).
        req = urllib.request.Request(
            f"http://{h}/internal/shards/max",
            headers={"X-Pilosa-Key": "k\xe9y"},
        )
        with pytest.raises(urllib.error.HTTPError) as he:
            urllib.request.urlopen(req, timeout=5)
        assert he.value.code == 403
    finally:
        s.close()


def test_cluster_key_file_validation(tmp_path):
    """One shared loader rejects the same misconfigurations for Server and
    the ctl CLI: missing, empty, and non-ASCII key files."""
    from pilosa_tpu.errors import PilosaError
    from pilosa_tpu.server.client import load_cluster_key

    with pytest.raises(PilosaError, match="cannot read"):
        load_cluster_key(str(tmp_path / "nope"))
    empty = tmp_path / "empty"
    empty.write_text("  \n")
    with pytest.raises(PilosaError, match="empty"):
        load_cluster_key(str(empty))
    emoji = tmp_path / "emoji"
    emoji.write_text("kéy")
    with pytest.raises(PilosaError, match="ASCII"):
        load_cluster_key(str(emoji))
    # Interior newline would blow up http.client at header-send time —
    # must be rejected at load, not on the first probe.
    twolines = tmp_path / "twolines"
    twolines.write_text("line1\nline2\n")
    with pytest.raises(PilosaError, match="one line"):
        load_cluster_key(str(twolines))
    ok = tmp_path / "ok"
    ok.write_text("hunter2\n")
    assert load_cluster_key(str(ok)) == "hunter2"


def test_config_toml_roundtrip(tmp_path):
    toml = Config().to_toml()
    p = tmp_path / "default.toml"
    p.write_text(toml)
    cfg = Config.load(str(p))
    assert cfg.bind == Config().bind
    assert cfg.cluster.replicas == Config().cluster.replicas


def test_config_toml_dump_covers_every_parsed_knob(tmp_path):
    """Regression (pilint R11's drift class — engine.plan-cache was
    parseable from TOML but missing from the to_toml dump): flip EVERY
    config field to a non-default value, dump, reload, and assert
    nothing silently reverted. A knob dropped from the dump loses the
    operator's setting on any resolved-config round trip."""
    import dataclasses

    def perturb(v):
        if isinstance(v, bool):
            return not v
        if isinstance(v, int):
            return v + 1
        if isinstance(v, float):
            return v + 0.5
        if isinstance(v, str):
            return v + "x"
        if isinstance(v, list):
            return list(v) + ["localhost:19999"]
        return v

    cfg = Config()
    for f in dataclasses.fields(cfg):
        section = getattr(cfg, f.name)
        if dataclasses.is_dataclass(section):
            for sf in dataclasses.fields(section):
                setattr(section, sf.name, perturb(getattr(section, sf.name)))
        else:
            setattr(cfg, f.name, perturb(section))
    p = tmp_path / "perturbed.toml"
    p.write_text(cfg.to_toml())
    back = Config.load(str(p))
    assert dataclasses.asdict(back) == dataclasses.asdict(cfg)


def test_generate_config(capsys):
    assert main(["generate-config"]) == 0
    out = capsys.readouterr().out
    assert "data-dir" in out and "[cluster]" in out


def test_config_command_with_flags(capsys):
    assert main(["config", "--bind", "0.0.0.0:9999"]) == 0
    assert 'bind = "0.0.0.0:9999"' in capsys.readouterr().out


@pytest.fixture
def server(tmp_path):
    s = Server(data_dir=str(tmp_path / "srv"), cache_flush_interval=0)
    s.open()
    yield s
    s.close()


def test_import_export_roundtrip(tmp_path, server, capsys):
    csv_path = tmp_path / "bits.csv"
    csv_path.write_text("1,10\n1,20\n2,30\n")
    rc = main([
        "import", "--host", f"localhost:{server.port}",
        "-i", "imp", "-f", "f", "--create", str(csv_path),
    ])
    assert rc == 0
    out_path = tmp_path / "out.csv"
    rc = main([
        "export", "--host", f"localhost:{server.port}",
        "-i", "imp", "-f", "f", "-o", str(out_path),
    ])
    assert rc == 0
    assert sorted(out_path.read_text().strip().splitlines()) == ["1,10", "1,20", "2,30"]


def test_import_int_field(tmp_path, server):
    csv_path = tmp_path / "vals.csv"
    csv_path.write_text("1,100\n2,250\n")
    rc = main([
        "import", "--host", f"localhost:{server.port}",
        "-i", "impv", "-f", "v", "--create",
        "--field-type", "int", "--field-min", "0", "--field-max", "1000",
        str(csv_path),
    ])
    assert rc == 0
    from pilosa_tpu.server.client import InternalClient

    resp = InternalClient().query(f"localhost:{server.port}", "impv", "Sum(field=v)")
    assert resp["results"][0] == {"value": 350, "count": 2}


def test_import_with_timestamps(tmp_path, server):
    csv_path = tmp_path / "ts.csv"
    csv_path.write_text("1,10,2018-01-02T00:00\n")
    rc = main([
        "import", "--host", f"localhost:{server.port}",
        "-i", "impt", "-f", "t", "--create",
        "--field-time-quantum", "YMD", str(csv_path),
    ])
    assert rc == 0
    from pilosa_tpu.server.client import InternalClient

    resp = InternalClient().query(
        f"localhost:{server.port}", "impt",
        "Range(t=1, 2018-01-01T00:00, 2018-01-03T00:00)",
    )
    assert resp["results"][0]["columns"] == [10]


def test_inspect_and_check(tmp_path, server, capsys):
    from pilosa_tpu.server.client import InternalClient

    client = InternalClient()
    client.create_index(f"localhost:{server.port}", "chk")
    client.create_field(f"localhost:{server.port}", "chk", "f")
    client.query(f"localhost:{server.port}", "chk", "Set(1, f=1)")
    frag_path = os.path.join(
        server.data_dir, "indexes", "chk", "f", "views", "standard", "fragments", "0"
    )
    assert os.path.exists(frag_path)
    assert main(["inspect", frag_path]) == 0
    out = capsys.readouterr().out
    assert "bits=1" in out
    assert main(["check", frag_path]) == 0
    # Corrupt file detected.
    bad = tmp_path / "bad"
    bad.write_bytes(b"\x00" * 32)
    assert main(["check", str(bad)]) == 1


def test_import_with_keys(tmp_path, server):
    csv_path = tmp_path / "keys.csv"
    csv_path.write_text("red,alice\nred,bob\nblue,alice\n")
    rc = main([
        "import", "--host", f"localhost:{server.port}",
        "-i", "impk", "-f", "color", "--create",
        "--index-keys", "--field-keys", str(csv_path),
    ])
    assert rc == 0
    from pilosa_tpu.server.client import InternalClient

    resp = InternalClient().query(
        f"localhost:{server.port}", "impk", 'Row(color="red")'
    )
    assert sorted(resp["results"][0]["keys"]) == ["alice", "bob"]
    resp = InternalClient().query(
        f"localhost:{server.port}", "impk", "TopN(color, n=2)"
    )
    assert resp["results"][0][0]["key"] == "red"
    assert resp["results"][0][0]["count"] == 2


def test_import_k_shorthand(tmp_path, server):
    """-k = --index-keys --field-keys (the reference's import -k)."""
    csv_path = tmp_path / "k.csv"
    csv_path.write_text("likes,alice\nlikes,bob\n")
    rc = main([
        "import", "--host", f"localhost:{server.port}",
        "-i", "impk2", "-f", "kf", "--create", "-k", str(csv_path),
    ])
    assert rc == 0
    from pilosa_tpu.server.client import InternalClient

    resp = InternalClient().query(
        f"localhost:{server.port}", "impk2", 'Count(Row(kf="likes"))'
    )
    assert resp["results"][0] == 2


def test_import_int_field_with_keys(tmp_path, server):
    csv_path = tmp_path / "kv.csv"
    csv_path.write_text("alice,42\nbob,58\n")
    rc = main([
        "import", "--host", f"localhost:{server.port}",
        "-i", "impkv", "-f", "v", "--create", "--index-keys",
        "--field-type", "int", "--field-min", "0", "--field-max", "100",
        str(csv_path),
    ])
    assert rc == 0
    from pilosa_tpu.server.client import InternalClient

    resp = InternalClient().query(f"localhost:{server.port}", "impkv", "Sum(field=v)")
    assert resp["results"][0] == {"value": 100, "count": 2}


def test_import_length_mismatch_is_400(server):
    import urllib.error
    import urllib.request

    from pilosa_tpu.server.client import InternalClient

    c = InternalClient()
    c.create_index(f"localhost:{server.port}", "mis", {"keys": True})
    c.create_field(f"localhost:{server.port}", "mis", "f", {"keys": True})
    req = urllib.request.Request(
        f"http://localhost:{server.port}/index/mis/field/f/import",
        data=json.dumps({"rowKeys": ["x", "y"], "columnKeys": ["a"]}).encode(),
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req)
    assert ei.value.code == 400
    assert "mismatch" in ei.value.read().decode()


def test_config_tls_and_cors_sections(tmp_path, monkeypatch):
    cfg_file = tmp_path / "cfg.toml"
    cfg_file.write_text(
        'bind = "https://localhost:4443"\n'
        '[tls]\ncertificate = "/c.pem"\nkey = "/k.pem"\nskip-verify = true\n'
        '[handler]\nallowed-origins = ["http://a/", "http://b/"]\n'
    )
    cfg = Config.load(str(cfg_file))
    assert cfg.tls.certificate_path == "/c.pem"
    assert cfg.tls.certificate_key_path == "/k.pem"
    assert cfg.tls.skip_verify is True
    assert cfg.handler.allowed_origins == ["http://a/", "http://b/"]
    # Round-trips through to_toml.
    (tmp_path / "dump.toml").write_text(cfg.to_toml())
    cfg2 = Config.load(str(tmp_path / "dump.toml"))
    assert cfg2.tls.certificate_path == "/c.pem"
    assert cfg2.handler.allowed_origins == ["http://a/", "http://b/"]
    # Env override.
    monkeypatch.setenv("PILOSA_TPU_HANDLER_ALLOWED_ORIGINS", "http://c/")
    assert Config.load(str(cfg_file)).handler.allowed_origins == ["http://c/"]
    # Flags (as parsed by the CLI) beat both.
    cfg3 = Config.load(str(cfg_file), {"allowed_origins": ["http://d/"],
                                       "tls_skip_verify": False})
    assert cfg3.handler.allowed_origins == ["http://d/"]
