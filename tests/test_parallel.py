"""Sharded query engine tests on the virtual 8-device CPU mesh.

Verifies the fast path produces identical results to the per-shard
reference path, that leaf tensors are actually sharded over the mesh, and
that cache invalidation tracks fragment generations.
"""

import jax
import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.parallel.engine import ShardedQueryEngine
from pilosa_tpu.parallel.mesh import default_mesh
from pilosa_tpu.pql.parser import parse


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def ex(holder):
    return Executor(holder, workers=0)


def plant(holder, ex, n_shards=5):
    """Bits for f=1 in every shard, f=2 in even shards, g=3 sparse."""
    idx = holder.create_index_if_not_exists("i")
    idx.create_field_if_not_exists("f")
    idx.create_field_if_not_exists("g")
    rng = np.random.default_rng(3)
    expected = {}
    for name, row, density in [("f", 1, 0.001), ("f", 2, 0.0005), ("g", 3, 0.0008)]:
        cols = []
        for s in range(n_shards):
            if name == "f" and row == 2 and s % 2:
                continue
            local = np.flatnonzero(rng.random(4096) < density * 256)
            cols.extend(int(s * SHARD_WIDTH + c) for c in local)
        fld = idx.field(name)
        fld.import_bits([row] * len(cols), cols)
        expected[(name, row)] = set(cols)
    return expected


def test_devices_available():
    assert len(jax.devices()) == 8


def test_engine_count_matches_per_shard(holder, ex):
    expected = plant(holder, ex)
    engine = ShardedQueryEngine(holder)
    shards = list(range(5))
    call = parse("Intersect(Row(f=1), Row(g=3))").calls[0]
    want = len(expected[("f", 1)] & expected[("g", 3)])
    assert engine.count("i", call, shards) == want
    # Union / difference / xor.
    for name, op in [("Union", set.union), ("Difference", set.difference), ("Xor", set.symmetric_difference)]:
        c = parse(f"{name}(Row(f=1), Row(f=2))").calls[0]
        want = len(op(expected[("f", 1)], expected[("f", 2)]))
        assert engine.count("i", c, shards) == want, name


def test_engine_bitmap_matches(holder, ex):
    expected = plant(holder, ex)
    engine = ShardedQueryEngine(holder)
    call = parse("Union(Row(f=1), Row(g=3))").calls[0]
    row = engine.bitmap("i", call, list(range(5)))
    assert set(row.columns().tolist()) == expected[("f", 1)] | expected[("g", 3)]


def test_engine_leaf_is_sharded(holder, ex):
    plant(holder, ex, n_shards=8)
    engine = ShardedQueryEngine(holder)
    from pilosa_tpu.parallel.engine import Leaf

    arr = engine._gather_leaf("i", Leaf("f", "standard", 1), tuple(range(8)))
    assert arr.shape[0] == 8
    # Data must actually be distributed across all 8 devices.
    assert len({s.device for s in arr.addressable_shards}) == 8


def test_engine_mesh_devices_knob(holder, ex):
    """[engine] mesh-devices pins the engine to the first N local
    devices — per-node programs then carry no cross-device all-reduces
    (the CPU concurrent-rendezvous hazard, docs/multichip.md) — and
    results stay bit-exact."""
    from pilosa_tpu.parallel import EngineConfig

    expected = plant(holder, ex)
    engine = ShardedQueryEngine(holder, config=EngineConfig(mesh_devices=1))
    assert engine.n_devices == 1
    call = parse("Intersect(Row(f=1), Row(g=3))").calls[0]
    want = len(expected[("f", 1)] & expected[("g", 3)])
    assert engine.count("i", call, list(range(5))) == want


def test_engine_executor_integration(holder, ex):
    expected = plant(holder, ex)
    want = len(expected[("f", 1)] & expected[("g", 3)])
    res = ex.execute("i", "Count(Intersect(Row(f=1), Row(g=3)))")
    assert res == [want]
    row = ex.execute("i", "Intersect(Row(f=1), Row(g=3))")[0]
    assert set(row.columns().tolist()) == expected[("f", 1)] & expected[("g", 3)]


def test_engine_cache_invalidation(holder, ex):
    plant(holder, ex)
    res1 = ex.execute("i", "Count(Row(f=1))")[0]
    # Mutate a row; the cached leaf tensor must be refreshed.
    ex.execute("i", f"Set({3 * SHARD_WIDTH + 77}, f=1)")
    res2 = ex.execute("i", "Count(Row(f=1))")[0]
    assert res2 == res1 + 1


def test_engine_bsi_range(holder, ex):
    idx = holder.create_index_if_not_exists("i")
    idx.create_field_if_not_exists("v", FieldOptions(type="int", min=0, max=100))
    cols = [1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH + 3, 3 * SHARD_WIDTH + 4]
    vals = [10, 20, 30, 40]
    idx.field("v").import_value(cols, vals)
    engine = ShardedQueryEngine(holder)
    call = parse("Range(v > 15)").calls[0]
    row = engine.bitmap("i", call, list(range(4)))
    assert row.columns().tolist() == cols[1:]
    call = parse("Range(15 < v < 35)").calls[0]
    assert engine.count("i", call, list(range(4))) == 2


def test_engine_topn_counts(holder, ex):
    expected = plant(holder, ex)
    engine = ShardedQueryEngine(holder)
    counts = engine.topn_counts("i", "f", [1, 2], list(range(5)))
    assert counts.tolist() == [len(expected[("f", 1)]), len(expected[("f", 2)])]
    src = parse("Row(g=3)").calls[0]
    counts = engine.topn_counts("i", "f", [1, 2], list(range(5)), src_call=src)
    assert counts.tolist() == [
        len(expected[("f", 1)] & expected[("g", 3)]),
        len(expected[("f", 2)] & expected[("g", 3)]),
    ]


def test_engine_padding_non_divisible(holder, ex):
    """5 shards on 8 devices: padded slots must not affect results."""
    expected = plant(holder, ex, n_shards=5)
    engine = ShardedQueryEngine(holder)
    call = parse("Row(f=1)").calls[0]
    assert engine.count("i", call, list(range(5))) == len(expected[("f", 1)])


def test_engine_count_batch_setops(holder, ex):
    """Vectorized batched counts match single-query counts, across batch
    sizes that exercise the pow2 padding (Q=1, 3, 5) and leaf dedup."""
    expected = plant(holder, ex)
    engine = ShardedQueryEngine(holder)
    shards = list(range(5))
    queries = [
        "Intersect(Row(f=1), Row(g=3))",
        "Intersect(Row(f=1), Row(f=2))",
        "Intersect(Row(f=2), Row(g=3))",
        "Intersect(Row(f=1), Row(g=3))",  # duplicate of the first
        "Intersect(Row(g=3), Row(f=1))",
    ]
    calls = [parse(q).calls[0] for q in queries]
    singles = [engine.count("i", c, shards) for c in calls]
    for q in (1, 3, 5):
        got = engine.count_batch("i", calls[:q], shards)
        assert got.tolist() == singles[:q], q
    # Same structure, different rows: correct counts, and the second run of
    # the same batch shape must not compile any new program (cache keyed on
    # structure + deduped batch size, not row ids). The 4 duplicate queries
    # are memoized within the batch and fanned back out.
    more = [parse("Intersect(Row(f=2), Row(f=1))").calls[0]] * 4
    got = engine.count_batch("i", more + calls[:1], shards)
    want = engine.count("i", more[0], shards)
    assert got.tolist() == [want] * 4 + singles[:1]
    n_progs = len(engine._count_fns)
    got2 = engine.count_batch("i", more + calls[:1], shards)
    assert len(engine._count_fns) == n_progs
    assert got2.tolist() == got.tolist()


def test_engine_count_batch_async_and_stack_invalidation(holder, ex):
    """count_batch_async returns valid device results, and a mutation
    between batches refreshes the resident stacked leaf tensor."""
    import numpy as np

    expected = plant(holder, ex)
    engine = ShardedQueryEngine(holder)
    shards = list(range(5))
    calls = [
        parse("Intersect(Row(f=1), Row(g=3))").calls[0],
        parse("Intersect(Row(f=1), Row(f=2))").calls[0],
    ]
    singles = [engine.count("i", c, shards) for c in calls]
    fut = engine.count_batch_async("i", calls, shards)
    assert np.asarray(fut)[: len(calls)].tolist() == singles

    # Mutate a leaf that participates in the batch; the cached stack must
    # be rebuilt (generation fingerprint mismatch), not served stale.
    frag = holder.fragment("i", "f", "standard", 0)
    col = 777
    was_set = frag.bit(1, col)
    if was_set:
        frag.clear_bit(1, col)
        expected[("f", 1)].discard(col)
    else:
        frag.set_bit(1, col)
        expected[("f", 1)].add(col)
    after = engine.count_batch("i", calls, shards).tolist()
    want = [
        len(expected[("f", 1)] & expected[("g", 3)]),
        len(expected[("f", 1)] & expected[("f", 2)]),
    ]
    assert after == want


def test_engine_leaf_cache_eviction_under_tiny_budget(holder, ex, monkeypatch):
    """Leaf-cache eviction mid-gather must not crash or corrupt results
    (regression: fingerprint was read back through the evicting cache)."""
    monkeypatch.setenv("PILOSA_LEAF_CACHE_BYTES", "8192")
    monkeypatch.setenv("PILOSA_STACK_CACHE_BYTES", "8192")
    expected = plant(holder, ex)
    engine = ShardedQueryEngine(holder)
    counts = engine.topn_counts("i", "f", list(range(40)), [0])
    in_shard0 = lambda cols: sum(1 for c in cols if c < SHARD_WIDTH)
    assert counts[1] == in_shard0(expected[("f", 1)])
    assert counts[2] == in_shard0(expected[("f", 2)])
    # Repeat (stack cache path) and a batched count under the same budget.
    counts2 = engine.topn_counts("i", "f", list(range(40)), [0])
    assert counts2.tolist() == counts.tolist()
    calls = [parse("Intersect(Row(f=1), Row(f=2))").calls[0]] * 3
    got = engine.count_batch("i", calls, list(range(5)))
    want = len(expected[("f", 1)] & expected[("f", 2)])
    assert got.tolist() == [want] * 3


def test_engine_memo_skips_device_on_repeat(holder, ex):
    """Hot-query result memo: a repeat query is answered host-side (memo
    hit) and invalidated by fragment generation bumps."""
    expected = plant(holder, ex)
    engine = ShardedQueryEngine(holder)
    shards = list(range(5))
    call = parse("Intersect(Row(f=1), Row(g=3))").calls[0]
    want = len(expected[("f", 1)] & expected[("g", 3)])
    assert engine.count("i", call, shards) == want
    base = dict(engine.counters)
    assert engine.count("i", call, shards) == want
    assert engine.counters["memo_hits"] == base["memo_hits"] + 1
    # A write to any member fragment invalidates via generation.
    fld = holder.index("i").field("f")
    new_col = 777_777
    fld.set_bit(1, new_col)
    got = engine.count("i", call, shards)
    in_g3 = new_col in expected[("g", 3)]
    assert got == want + (1 if in_g3 else 0)


def test_topn_shard_counts_memo_and_invalidation(holder, ex):
    """Repeat TopN count-matrix requests are memo hits (any row order —
    canonical keying), and a write to a member fragment invalidates."""
    plant(holder, ex)
    engine = ShardedQueryEngine(holder)
    shards = list(range(5))
    rows = [2, 1]
    a1, _, _ = engine.topn_shard_counts("i", "f", rows, shards)
    base = dict(engine.counters)
    a2, _, _ = engine.topn_shard_counts("i", "f", [1, 2], shards)  # reordered
    assert engine.counters["memo_hits"] == base["memo_hits"] + 1
    import numpy as np

    np.testing.assert_array_equal(a1[0], a2[1])  # row 2
    np.testing.assert_array_equal(a1[1], a2[0])  # row 1
    # A write to row 1's fragment invalidates the entry.
    assert holder.fragment("i", "f", "standard", 0).set_bit(1, 5000)
    a3, _, _ = engine.topn_shard_counts("i", "f", rows, shards)
    assert int(a3[1].sum()) == int(a1[1].sum()) + 1
    assert engine.counters["memo_misses"] > base["memo_misses"]


def test_bsi_val_count_memo_and_invalidation(holder, ex):
    from pilosa_tpu.core.field import FieldOptions

    idx = holder.index("i") or holder.create_index("i")
    idx.create_field_if_not_exists("v", FieldOptions(type="int", min=0, max=1000))
    ex.execute("i", "SetValue(col=1, v=5)")
    ex.execute("i", "SetValue(col=2, v=7)")
    engine = ShardedQueryEngine(holder)
    depth = idx.field("v").bsi_group("v").bit_depth()
    counts1 = engine.bsi_val_count("i", "v", "sum", depth, [0])
    base = dict(engine.counters)
    counts2 = engine.bsi_val_count("i", "v", "sum", depth, [0])
    assert engine.counters["memo_hits"] == base["memo_hits"] + 1
    import numpy as np

    np.testing.assert_array_equal(counts1, counts2)
    ex.execute("i", "SetValue(col=3, v=9)")
    counts3 = engine.bsi_val_count("i", "v", "sum", depth, [0])
    assert int(counts3[depth]) == int(counts1[depth]) + 1


def test_gather_kernel_multi_device_shard_map(holder, ex, monkeypatch):
    """The Pallas gather kernel partitions over a multi-device mesh via
    shard_map + psum: batched counts forced onto the kernel (interpret
    mode on CPU) must equal the XLA-fallback singles on the 8-device
    mesh."""
    expected = plant(holder, ex, n_shards=8)
    engine = ShardedQueryEngine(holder)
    assert engine.n_devices == 8
    shards = list(range(8))
    pairs = [("f", 1, "g", 3), ("f", 1, "f", 2), ("f", 2, "g", 3)]
    calls = [
        parse(f"Intersect(Row({fa}={ra}), Row({fb}={rb}))").calls[0]
        for fa, ra, fb, rb in pairs
    ]
    singles = [engine.count("i", c, shards) for c in calls]
    # Anchor to planted ground truth so a bug shared by both device paths
    # cannot hide.
    want = [
        len(expected[(fa, ra)] & expected[(fb, rb)]) for fa, ra, fb, rb in pairs
    ]
    assert singles == want

    monkeypatch.setenv("PILOSA_PALLAS_BATCH", "1")
    kernel_engine = ShardedQueryEngine(holder)
    got = kernel_engine.count_batch("i", calls, shards)
    assert got.tolist() == singles
