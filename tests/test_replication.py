"""Durable write replication: hinted handoff, tunable write consistency,
hint-aware anti-entropy (docs/durability.md "Write-path consistency",
`pilosa_tpu/cluster/hints.py`).

Three tiers of proof:
  - unit: hint record codec + torn tails, TTL/budget/marker lifecycle,
    delivery state machine against a fake client, consistency math, the
    typed retryable 503 shape;
  - integration: a 3-node replica_n=3 cluster where a replica flaps
    dead -> alive under write-consistency=quorum (THE tier-1 chaos
    test, seed-pinned, fake breaker clock) — every ack met its level,
    hints drain to byte-identical fragments, breakers/health converge;
  - the subprocess kill -9 durability twin lives in
    tests/test_durability.py (torn hint tail truncates, never replays
    garbage).
"""

import io
import os
import socket
import time

import numpy as np
import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.cluster.health import CLOSED, HealthRegistry, ResilienceConfig
from pilosa_tpu.cluster.hints import (
    HintRecord,
    HintStore,
    ReplicationConfig,
    decode_records,
    encode_record,
)
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.errors import WriteConsistencyError
from pilosa_tpu.server.client import ClientError, InternalClient
from pilosa_tpu.server.server import Server
from pilosa_tpu.storage.bitmap import (
    OP_ADD,
    OP_REMOVE,
    decode_op_records,
    encode_bulk_op,
    encode_op,
)

from .conftest import FakeClock


class _Frag:
    """Fragment-shaped identity carrier for HintStore.add."""

    def __init__(self, index="i", field="f", view="standard", shard=0):
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard


class _Node:
    def __init__(self, node_id, uri=None):
        self.id = node_id
        self.uri = uri or node_id


# ------------------------------------------------------------- unit: config


def test_replication_config_validation_and_levels():
    cfg = ReplicationConfig().validate()
    assert cfg.write_consistency == "one"
    assert cfg.required_owners(3) == 1
    assert ReplicationConfig(
        write_consistency="quorum").required_owners(3) == 2
    assert ReplicationConfig(
        write_consistency="quorum").required_owners(2) == 2
    assert ReplicationConfig(
        write_consistency="quorum").required_owners(5) == 3
    assert ReplicationConfig(write_consistency="all").required_owners(3) == 3
    with pytest.raises(ValueError):
        ReplicationConfig(write_consistency="most").validate()
    with pytest.raises(ValueError):
        ReplicationConfig(hint_ttl=0).validate()
    with pytest.raises(ValueError):
        ReplicationConfig(deliver_batch_bytes=0).validate()


# -------------------------------------------------------------- unit: codec


def test_hint_record_roundtrip_and_torn_tail():
    rec = HintRecord(1234.5, "idx", "fld", "standard_2020", 42,
                     encode_op(OP_ADD, 7))
    blob = encode_record(rec) + encode_record(
        HintRecord(1.0, "i2", "", "", 3, b""))  # marker
    out = list(decode_records(blob))
    assert len(out) == 2
    got, end1 = out[0]
    assert (got.index, got.field, got.view, got.shard) == (
        "idx", "fld", "standard_2020", 42)
    assert got.ops == rec.ops and not got.marker
    assert out[1][0].marker and out[1][0].shard == 3
    # A torn tail (half a record) stops the decode cleanly at the last
    # whole boundary; corrupt bytes stop it too.
    assert [r.shard for r, _ in decode_records(blob[:end1 + 5])] == [42]
    flipped = blob[:end1] + bytes([blob[end1] ^ 0xFF]) + blob[end1 + 1:]
    assert [r.shard for r, _ in decode_records(flipped)] == [42]


def test_decode_op_records_orders_and_strictness():
    data = (encode_op(OP_ADD, 5) + encode_bulk_op([1, 2], [3])
            + encode_op(OP_REMOVE, 5))
    recs = decode_op_records(data)
    assert [(a.tolist(), r.tolist()) for a, r in recs] == [
        ([5], []), ([1, 2], [3]), ([], [5])]
    from pilosa_tpu.errors import CorruptFragmentError

    with pytest.raises(CorruptFragmentError):
        decode_op_records(data + b"\x01\x02")  # trailing garbage = fault


# -------------------------------------------------------------- unit: store


def test_hint_store_reload_truncates_torn_tail(tmp_path):
    hs = HintStore(str(tmp_path), ReplicationConfig())
    assert hs.add("peer:1", "i", 0, [(_Frag(), encode_op(OP_ADD, 1))])
    assert hs.add("peer:1", "i", 1, [(_Frag(shard=1), encode_op(OP_ADD, 2))])
    hs.close()
    log = os.path.join(str(tmp_path), "peer%3A1", "log")
    whole = os.path.getsize(log)
    with open(log, "ab") as f:
        f.write(b"\x00gar\xffbage")
    hs2 = HintStore(str(tmp_path), ReplicationConfig())
    assert hs2.pending("peer:1") == 2
    assert hs2.snapshot()["hints_truncated"] == 1
    assert os.path.getsize(log) == whole  # garbage cut, records kept
    assert [r.shard for r in hs2.records("peer:1")] == [0, 1]
    hs2.close()


def test_hint_store_budget_overflow_flags_shard(tmp_path):
    hs = HintStore(str(tmp_path),
                   ReplicationConfig(hint_max_bytes=200))
    big = encode_bulk_op(np.arange(64, dtype=np.uint64), None)
    assert hs.add("p:1", "i", 0, [(_Frag(), encode_op(OP_ADD, 1))])
    assert not hs.add("p:1", "i", 5, [(_Frag(shard=5), big)])
    snap = hs.snapshot()
    assert snap["hints_overflow"] == 1
    assert ("i", 5) in hs.priority_shards()
    assert ("i", 0) in hs.priority_shards()  # pending hints count too
    hs.note_synced("i", 5)
    assert ("i", 5) not in hs.priority_shards()
    hs.close()


def test_oversize_record_refused_not_wedged(tmp_path, monkeypatch):
    """A record the decoder would classify as a torn tail must be
    refused at APPEND time: once in the log it could never be decoded,
    the cursor could never pass it, and the FIFO pre-check would queue
    every later write behind a permanently wedged drain."""
    from pilosa_tpu.cluster import hints as hints_mod

    monkeypatch.setattr(hints_mod, "_MAX_RECORD", 64)
    hs = HintStore(str(tmp_path), ReplicationConfig())
    big = encode_bulk_op(np.arange(32, dtype=np.uint64), None)
    assert not hs.add("p:1", "i", 3, [(_Frag(shard=3), big)])
    assert hs.pending("p:1") == 0  # nothing undecodable was appended
    assert hs.snapshot()["hints_overflow"] == 1
    assert ("i", 3) in hs.priority_shards()  # sweep owns the repair
    assert hs.add("p:1", "i", 0, [(_Frag(), encode_op(OP_ADD, 1))])
    hs.close()


def test_hint_append_failpoint_refuses_durably(tmp_path):
    hs = HintStore(str(tmp_path), ReplicationConfig())
    try:
        failpoints.configure("hint-append", "error", count=1)
        assert not hs.add("p:1", "i", 0, [(_Frag(), encode_op(OP_ADD, 1))])
        assert hs.snapshot()["append_errors"] == 1
        assert ("i", 0) in hs.priority_shards()  # sweep backstop flagged
        assert hs.add("p:1", "i", 0, [(_Frag(), encode_op(OP_ADD, 1))])
    finally:
        failpoints.reset()
        hs.close()


def test_marker_hint_without_capture(tmp_path):
    hs = HintStore(str(tmp_path), ReplicationConfig())
    assert hs.add("p:1", "i", 9, None)  # no local replica -> marker
    assert hs.snapshot()["hints_markers"] == 1
    assert ("i", 9) in hs.priority_shards()
    recs = hs.records("p:1")
    assert len(recs) == 1 and recs[0].marker
    hs.close()


class _FakeHintClient:
    def __init__(self, fail=None):
        self.sent = []  # (peer_uri, index, field, view, shard, ops)
        self.fail = fail  # None | ClientError to raise

    def send_hint_ops(self, node, index, field, view, shard, data):
        if self.fail is not None:
            raise self.fail
        self.sent.append((node.uri, index, field, view, shard, data))


class _FakeCluster:
    def __init__(self, nodes, health):
        self._nodes = {n.id: n for n in nodes}
        self.health = health

    def node_by_id(self, node_id):
        return self._nodes.get(node_id)


def test_delivery_order_checkpoint_and_drain(tmp_path):
    clock = FakeClock()
    hs = HintStore(str(tmp_path), ReplicationConfig(), clock=clock)
    health = HealthRegistry(ResilienceConfig(), clock=clock)
    cluster = _FakeCluster([_Node("p:1")], health)
    for i in range(5):
        assert hs.add("p:1", "i", i % 2,
                      [(_Frag(shard=i % 2), encode_op(OP_ADD, i))])
    client = _FakeHintClient()
    assert hs.deliver_once(cluster, client) == 5
    # In order, correct addressing, drained + compacted.
    assert [s for (_, _, _, _, s, _) in client.sent] == [0, 1, 0, 1, 0]
    assert [decode_op_records(d)[0][0].tolist()
            for (*_, d) in client.sent] == [[0], [1], [2], [3], [4]]
    assert hs.pending("p:1") == 0
    snap = hs.snapshot()
    assert snap["hints_delivered"] == 5 and snap["drains"] == 1
    assert os.path.getsize(os.path.join(str(tmp_path), "p%3A1", "log")) == 0
    # Drained shards keep ONE verifying-priority-sweep flag: the FIFO
    # covers writes that saw the backlog, but a write racing the very
    # first in-flight failing forward can land newer state on the peer
    # before its hint — the sweep closes that window.
    assert {("i", 0), ("i", 1)} <= hs.priority_shards()
    hs.note_synced("i", 0)
    hs.note_synced("i", 1)
    assert hs.priority_shards() == set()
    hs.close()


def test_delivery_transport_failure_drives_breaker_and_retries(tmp_path):
    clock = FakeClock()
    hs = HintStore(str(tmp_path), ReplicationConfig(), clock=clock)
    health = HealthRegistry(
        ResilienceConfig(breaker_failures=1, breaker_backoff=1.0),
        clock=clock)
    cluster = _FakeCluster([_Node("p:1")], health)
    hs.add("p:1", "i", 0, [(_Frag(), encode_op(OP_ADD, 1))])
    bad = _FakeHintClient(fail=ClientError("conn refused", status=0))
    assert hs.deliver_once(cluster, bad) == 0
    assert hs.pending("p:1") == 1  # cursor NOT advanced
    assert health.state("p:1") != CLOSED  # failure recorded -> breaker
    # While the breaker backs off, delivery doesn't even try.
    good = _FakeHintClient()
    assert hs.deliver_once(cluster, good) == 0
    assert good.sent == []
    # Backoff elapses: the delivery attempt IS the half-open probe and
    # its success re-closes the breaker.
    clock.advance(1.5)
    assert hs.deliver_once(cluster, good) == 1
    assert health.state("p:1") == CLOSED
    assert hs.pending("p:1") == 0
    hs.close()


def test_delivery_4xx_skips_unreplayable_record(tmp_path):
    clock = FakeClock()
    hs = HintStore(str(tmp_path), ReplicationConfig(), clock=clock)
    health = HealthRegistry(ResilienceConfig(), clock=clock)
    cluster = _FakeCluster([_Node("p:1")], health)
    hs.add("p:1", "i", 0, [(_Frag(field="deleted"), encode_op(OP_ADD, 1))])
    hs.add("p:1", "i", 0, [(_Frag(), encode_op(OP_ADD, 2))])

    class _Picky(_FakeHintClient):
        def send_hint_ops(self, node, index, field, view, shard, data):
            if field == "deleted":
                raise ClientError("field not found", status=400)
            super().send_hint_ops(node, index, field, view, shard, data)

    client = _Picky()
    assert hs.deliver_once(cluster, client) == 1
    assert hs.pending("p:1") == 0  # rejected record skipped, not wedged
    snap = hs.snapshot()
    assert snap["hints_rejected"] == 1 and snap["hints_delivered"] == 1
    assert health.state("p:1") == CLOSED  # 4xx is transport success
    hs.close()


def test_delivery_ttl_expiry_flags_for_sync(tmp_path):
    clock = FakeClock()
    hs = HintStore(str(tmp_path), ReplicationConfig(hint_ttl=10.0),
                   clock=clock)
    health = HealthRegistry(ResilienceConfig(), clock=clock)
    cluster = _FakeCluster([_Node("p:1")], health)
    hs.add("p:1", "i", 4, [(_Frag(shard=4), encode_op(OP_ADD, 1))])
    clock.advance(11.0)
    client = _FakeHintClient()
    assert hs.deliver_once(cluster, client) == 0
    assert client.sent == []  # never replays a stale op
    assert hs.pending("p:1") == 0
    assert hs.snapshot()["hints_expired"] == 1
    assert ("i", 4) in hs.priority_shards()
    hs.close()


def test_hint_deliver_failpoint_targets_peer(tmp_path):
    clock = FakeClock()
    hs = HintStore(str(tmp_path), ReplicationConfig(), clock=clock)
    health = HealthRegistry(
        ResilienceConfig(breaker_failures=1, breaker_backoff=0.1),
        clock=clock)
    cluster = _FakeCluster([_Node("p:1", uri="peer-a:1")], health)
    hs.add("p:1", "i", 0, [(_Frag(), encode_op(OP_ADD, 1))])
    client = _FakeHintClient()
    try:
        failpoints.configure("hint-deliver@peer-a:1", "drop")
        assert hs.deliver_once(cluster, client) == 0
        assert hs.pending("p:1") == 1
        assert hs.snapshot()["deliver_errors"] == 1
    finally:
        failpoints.reset()
    clock.advance(0.5)
    assert hs.deliver_once(cluster, client) == 1
    hs.close()


def test_departed_peer_hints_pruned(tmp_path):
    hs = HintStore(str(tmp_path), ReplicationConfig())
    health = HealthRegistry(ResilienceConfig())
    hs.add("gone:1", "i", 0, [(_Frag(), encode_op(OP_ADD, 1))])
    cluster = _FakeCluster([], health)  # peer no longer in membership
    assert hs.deliver_once(cluster, _FakeHintClient()) == 0
    assert hs.pending("gone:1") == 0
    assert not os.path.exists(os.path.join(str(tmp_path), "gone%3A1", "log"))
    hs.close()


# ------------------------------------------------ unit: typed 503 semantics


def test_write_consistency_error_is_node_alive_shaped():
    from pilosa_tpu.executor import _is_node_failure

    e = ClientError("POST ...: 503 "
                    '{"error": "write consistency not met: ..."}', status=503)
    assert not _is_node_failure(e)
    assert _is_node_failure(ClientError("boom", status=503))
    assert _is_node_failure(ClientError("conn", status=0))


def test_handler_maps_write_consistency_to_retryable_503():
    from pilosa_tpu.server.handler import Handler

    class _API:
        class server:
            long_query_time = 0

    h = Handler.__new__(Handler)
    h.api = _API()
    h.logger = None
    h.internal_key = None

    class _Route:
        method = "POST"

        import re
        regex = re.compile(r"^/x$")

        @staticmethod
        def fn(**kw):
            raise WriteConsistencyError(
                "applied on 1/3 owners", level="quorum", required=2,
                applied=1)

    h.routes = [_Route()]
    status, ctype, payload, extra = h.dispatch("POST", "/x", {}, b"")
    assert status == 503
    assert extra.get("Retry-After") == "1"
    assert b"write consistency" in payload


# -------------------------------------------------- integration: cluster


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def quorum_cluster(tmp_path):
    """3-node replica_n=3 cluster under write-consistency=quorum with a
    shared fake breaker clock and manual monitors (background hint
    delivery stays ON — it is part of what's under test)."""
    clock = FakeClock()
    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]

    def mk(i, port):
        return Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=port,
            cluster_hosts=hosts,
            replica_n=3,
            hasher=ModHasher(),
            cache_flush_interval=0,
            anti_entropy_interval=0,
            member_monitor_interval=0,
            executor_workers=0,
            resilience_config=ResilienceConfig(
                breaker_backoff=0.2, breaker_backoff_max=1.0),
            replication_config=ReplicationConfig(
                write_consistency="quorum", deliver_interval=0.1),
        )

    servers = [mk(i, p).open() for i, p in enumerate(ports)]
    for s in servers:
        s.cluster.health.clock = clock
    yield servers, hosts, clock, mk
    failpoints.reset()
    for s in servers:
        try:
            s.close()
        except Exception:
            pass


@pytest.mark.chaos
def test_quorum_writes_replica_flap_hints_drain(quorum_cluster, tmp_path):
    """THE replication chaos test (tier-1, seed-pinned by construction —
    no randomness — fake breaker clock): a replica flaps dead -> alive
    under write-consistency=quorum writes. Every ack met its level (2/3
    owners applied, zero WriteConsistencyUnmet), misses cost hint
    appends (never a connect timeout per write once the breaker opened),
    the hint log drains to byte-identical fragments on the returned
    replica, and breakers/health converge CLOSED."""
    servers, hosts, clock, mk = quorum_cluster
    client = InternalClient(timeout=10.0)
    s0 = servers[0]
    h0 = hosts[0]
    client.create_index(h0, "qr")
    client.create_field(h0, "qr", "f")
    time.sleep(0.05)

    def counter(name):
        return s0.stats.snapshot()["counters"].get(name, 0)

    # Phase 1: healthy quorum writes across 2 shards.
    cols = [s * SHARD_WIDTH + 10 + k for s in range(2) for k in range(3)]
    for col in cols[:3]:
        assert client.query(h0, "qr", f"Set({col}, f=1)")["results"][0]

    # Phase 2: one replica dies. replica_n=3 quorum=2: local + one
    # forward still ack every write; the dead peer's misses hint.
    dead = servers[2]
    dead_id, dead_port = dead.node.id, dead.port
    dead.close()
    for col in cols[3:]:
        assert client.query(h0, "qr", f"Set({col}, f=1)")["results"][0]
    assert counter("WriteConsistencyUnmet") == 0
    assert counter("WriteForwardHinted") >= 2
    # After breaker detection, writes stop paying transport failures:
    # one detection failure, the rest are O(batch) hint appends.
    assert counter("WriteForwardFailed") <= 1 + 1  # probe expiry slack
    assert s0.hints.pending(dead_id) >= 2
    # The dead peer's shards are first in line for anti-entropy.
    assert any(idx == "qr" for idx, _ in s0.hints.priority_shards())

    # Phase 3: replica returns. Breaker re-closes (monitor probe), the
    # delivery daemon drains the log, and fragments converge
    # byte-identically WITHOUT waiting for an anti-entropy sweep.
    revived = mk(2, dead_port)
    revived.open()
    revived.cluster.health.clock = clock
    try:
        clock.advance(2.0)  # any breaker backoff has elapsed
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and s0.hints.pending(dead_id):
            for s in servers[:2]:
                s._monitor_members()
            time.sleep(0.05)
        assert s0.hints.pending(dead_id) == 0

        for shard in range(2):
            frag0 = s0.holder.fragment("qr", "f", "standard", shard)
            fragX = revived.holder.fragment("qr", "f", "standard", shard)
            if frag0 is None:
                assert fragX is None
                continue
            assert fragX is not None
            b0, bX = io.BytesIO(), io.BytesIO()
            frag0.write_to(b0)
            fragX.write_to(bX)
            assert b0.getvalue() == bX.getvalue(), f"shard {shard} diverged"
        # Every owner answers the full count: no lost acked writes.
        for h in (hosts[0], hosts[1], f"localhost:{revived.port}"):
            got = client.query(h, "qr", "Count(Row(f=1))")
            assert got["results"][0] == len(cols)

        # Health converged: every breaker CLOSED, nobody unavailable.
        for s in servers[:2] + [revived]:
            snap = s.cluster.health.snapshot()
            for pid, p in snap["peers"].items():
                assert p["state"] == CLOSED, (pid, snap)
            assert s.cluster.unavailable == set()
        snap = s0.hints.snapshot()
        assert snap["drains"] >= 1
        assert snap["hints_delivered"] >= 2
    finally:
        revived.close()


def test_unmet_quorum_is_retryable_503_over_http(quorum_cluster):
    """With TWO of three owners dead, quorum (2) cannot be met: the
    write surfaces as a retryable 503 whose body names the level — and
    the local apply stands (no rollback), so a later recovered cluster
    converges from hints/anti-entropy rather than losing the bit."""
    servers, hosts, clock, _ = quorum_cluster
    client = InternalClient(timeout=10.0)
    s0 = servers[0]
    h0 = hosts[0]
    client.create_index(h0, "q2")
    client.create_field(h0, "q2", "f")
    time.sleep(0.05)
    assert client.query(h0, "q2", "Set(1, f=3)")["results"][0]
    servers[1].close()
    servers[2].close()
    with pytest.raises(ClientError) as ei:
        # Two forwards fail/hint -> applied=1 < quorum=2.
        client.query(h0, "q2", "Set(2, f=3)")
    assert ei.value.status == 503
    assert "write consistency" in str(ei.value)
    assert "quorum" in str(ei.value)
    # No rollback: the local apply stands, hints cover the dead peers.
    frag = s0.holder.fragment("q2", "f", "standard", 0)
    assert frag.row_count(3) == 2
    assert s0.stats.snapshot()["counters"].get("WriteConsistencyUnmet") >= 1


def test_total_owner_loss_is_retryable_503(quorum_cluster):
    """Satellite regression: 'write failed on all owners' used to raise
    a plain QueryError (400, client-error shaped). Total owner loss is
    transient — it must surface as the same typed retryable 503 so
    clients and retry budgets treat it as such."""
    servers, hosts, clock, _ = quorum_cluster
    client = InternalClient(timeout=10.0)
    s0 = servers[0]
    h0 = hosts[0]
    client.create_index(h0, "tl")
    client.create_field(h0, "tl", "f")
    time.sleep(0.05)
    # replica_n == n_nodes: every node owns every shard, so make the
    # OTHER two nodes the only live appliers impossible — kill them and
    # fail the local apply path by... simplest: ask a node that owns the
    # shard while the other owners are dead under level=all.
    servers[1].close()
    servers[2].close()
    # Direct executor-level proof of the degenerate case: zero owners
    # applied (local_fn raising the same transport shape is not a real
    # path — instead drive a non-owner coordinator via a fake cluster).
    from pilosa_tpu.cluster.node import Cluster, Node
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    nodes = [Node(id="n0"), Node(id="n1")]
    cluster = Cluster(node=nodes[0], nodes=nodes, replica_n=1,
                      hasher=ModHasher())

    class _DeadClient:
        def query_node(self, node, index, query, shards=None, remote=True):
            raise ClientError("conn refused", status=0)

    holder = Holder(None)
    holder.open()
    idx = holder.create_index("tl")
    idx.create_field("f")
    remote_shard = next(
        s for s in range(4)
        if cluster.shard_nodes("tl", s)[0].id == "n1")
    ex = Executor(holder, cluster=cluster, client=_DeadClient(), workers=0)
    with pytest.raises(WriteConsistencyError) as ei:
        ex.execute("tl", f"Set({remote_shard * SHARD_WIDTH + 1}, f=1)",
                   shards=[remote_shard])
    assert ei.value.applied == 0
    holder.close()


# -------------------------------------------- hint-aware anti-entropy order


def test_syncer_orders_hinted_shards_first(quorum_cluster):
    """The anti-entropy sweep visits shards with pending/expired hints
    FIRST instead of their stable position in the full-holder walk, and
    settles the priority flags afterwards."""
    from pilosa_tpu.cluster.syncer import HolderSyncer

    servers, hosts, clock, _ = quorum_cluster
    client = InternalClient(timeout=10.0)
    s0 = servers[0]
    h0 = hosts[0]
    client.create_index(h0, "sy")
    client.create_field(h0, "sy", "f")
    time.sleep(0.05)
    n_shards = 4
    for shard in range(n_shards):
        client.query(h0, "sy", f"Set({shard * SHARD_WIDTH + 1}, f=1)")

    # Flag a LATE shard as hint-priority (marker: no captured bytes).
    # The marker's peer is a real member so the delivery daemon can
    # drain the record; the needs-sync flag outlives the drain and is
    # what the sweep both orders on and settles.
    target = n_shards - 1
    s0.hints.add(servers[1].node.id, "sy", target, None)
    assert ("sy", target) in s0.hints.priority_shards()

    order = []
    syncer = HolderSyncer(s0)
    orig = syncer._sync_fragment

    def spy(index, field, view, shard, replicas):
        order.append((index, shard))
        return orig(index, field, view, shard, replicas)

    syncer._sync_fragment = spy
    syncer.sync_holder()
    assert order, "sweep visited nothing"
    assert order[0] == ("sy", target), order
    # The completed sweep settled the needs-sync flag; the background
    # daemon drains the marker record itself (idempotent), after which
    # nothing flags the shard anymore.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (
            ("sy", target) in s0.hints.priority_shards()):
        time.sleep(0.05)
    assert ("sy", target) not in s0.hints.priority_shards()


def test_syncer_keeps_flag_when_no_replica_reachable(quorum_cluster):
    """Review fix: a sweep that SKIPS a hint-flagged shard because every
    remote replica is down must not settle its flag — the outage that
    created the divergence would otherwise erase its priority ordering
    for the sweep that finally can repair it."""
    from pilosa_tpu.cluster.syncer import HolderSyncer

    servers, hosts, clock, _ = quorum_cluster
    client = InternalClient(timeout=10.0)
    s0 = servers[0]
    h0 = hosts[0]
    client.create_index(h0, "nr")
    client.create_field(h0, "nr", "f")
    time.sleep(0.05)
    client.query(h0, "nr", f"Set(1, f=1)")
    s0.hints.add(servers[1].node.id, "nr", 0, None)  # flag shard 0
    assert ("nr", 0) in s0.hints.priority_shards()
    for peer in (servers[1], servers[2]):
        s0.cluster.health.force_down(peer.node.id)
    HolderSyncer(s0).sync_holder()  # zero reachable replicas: no repair
    assert ("nr", 0) in s0.hints.priority_shards()
    for peer in (servers[1], servers[2]):
        s0.cluster.health.force_up(peer.node.id)
    HolderSyncer(s0).sync_holder()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (
            ("nr", 0) in s0.hints.priority_shards()):
        time.sleep(0.05)  # daemon drains the marker record itself
    assert ("nr", 0) not in s0.hints.priority_shards()


def test_spawn_jitter_clamped(tmp_path):
    """Review fix: jitter is a FRACTION — a percent-vs-fraction slip
    (jitter=20) must clamp rather than make the sweep wait negative
    (back-to-back sweeps: the stampede the knob exists to prevent)."""
    s = Server(data_dir=str(tmp_path / "n0"), port=0,
               anti_entropy_jitter=20.0)
    try:
        assert s.anti_entropy_jitter == 1.0
    finally:
        s.close()


def test_anti_entropy_jitter_and_pace_plumbing(tmp_path):
    """[anti-entropy] jitter/pace ride Config -> Server -> HolderSyncer;
    jitter=0 restores the fixed timer (exactness matters for tests)."""
    from pilosa_tpu.cluster.syncer import HolderSyncer
    from pilosa_tpu.config import Config

    cfg = Config()
    cfg._apply_dict({"anti-entropy":
                     {"interval": 5.0, "jitter": 0.25, "pace": 0.5}})
    assert cfg.anti_entropy.jitter == 0.25
    assert cfg.anti_entropy.pace == 0.5
    s = Server(data_dir=str(tmp_path / "n0"), port=0,
               anti_entropy_jitter=0.25, anti_entropy_pace=0.5)
    try:
        assert s.anti_entropy_jitter == 0.25
        assert s.anti_entropy_pace == 0.5
        assert HolderSyncer(s).pace == 0.5
    finally:
        s.close()


# ------------------------------------------------------- capture mechanics


def test_capture_hint_ops_is_thread_local_and_scoped():
    from pilosa_tpu.core.fragment import Fragment, capture_hint_ops

    frag = Fragment(None, "i", "f", "standard", 0)
    frag.open()
    grabbed: list = []
    with capture_hint_ops(grabbed):
        frag.set_bit(1, 3)
        frag.bulk_import(np.array([2], dtype=np.uint64),
                         np.array([4], dtype=np.uint64))
    frag.set_bit(1, 5)  # outside the capture: not recorded
    assert len(grabbed) == 2
    assert all(f is frag for f, _ in grabbed)
    ops = b"".join(b for _, b in grabbed)
    recs = decode_op_records(ops)
    assert recs[0][0].tolist() == [1 * SHARD_WIDTH + 3]
    assert recs[1][0].tolist() == [2 * SHARD_WIDTH + 4]
    frag.close()


def test_apply_hint_positions_is_idempotent():
    from pilosa_tpu.core.fragment import Fragment

    frag = Fragment(None, "i", "f", "standard", 0)
    frag.open()
    adds = np.array([5, SHARD_WIDTH + 6], dtype=np.uint64)
    rems = np.array([7], dtype=np.uint64)
    frag.apply_hint_positions(adds, rems)
    before = frag.row_count(0), frag.row_count(1)
    frag.apply_hint_positions(adds, rems)  # redelivery: harmless
    assert (frag.row_count(0), frag.row_count(1)) == before
    assert frag.bit(0, 5) and frag.bit(1, 6) and not frag.bit(0, 7)
    frag.close()
