"""Unit tests for the peer fault-tolerance layer (cluster/health.py):
circuit breaker lifecycle, retry budget, hedging math, the DownView set
facade, and the executor integration (zero connects while a breaker is
open, budget-gated replica re-map, hedged remote reads)."""

import pytest

from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.cluster.health import (
    CLOSED, HALF_OPEN, OPEN, HealthRegistry, ResilienceConfig,
)
from pilosa_tpu.cluster.node import Cluster, Node
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.errors import PilosaError
from pilosa_tpu.executor import Executor
from pilosa_tpu.server.client import ClientError


def make_health(clock, **kw):
    return HealthRegistry(ResilienceConfig(**kw).validate(), clock=clock)


# ----------------------------------------------------------- breaker core


def test_breaker_opens_after_threshold(fake_clock):
    h = make_health(fake_clock, breaker_failures=3)
    h.record_failure("n1")
    h.record_failure("n1")
    assert h.state("n1") == CLOSED and not h.is_down("n1")
    h.record_failure("n1")
    assert h.state("n1") == OPEN and h.is_down("n1")
    assert h.counters["breaker_opened"] == 1


def test_breaker_success_resets_streak(fake_clock):
    h = make_health(fake_clock, breaker_failures=2)
    h.record_failure("n1")
    h.record_success("n1")
    h.record_failure("n1")
    assert h.state("n1") == CLOSED  # streak broken by the success


def test_breaker_half_open_single_probe_and_reclose(fake_clock):
    h = make_health(fake_clock, breaker_backoff=1.0)
    h.record_failure("n1")  # default threshold 1 -> OPEN
    assert not h.allow_request("n1")
    assert h.counters["breaker_short_circuits"] == 1
    fake_clock.advance(1.0)
    # Backoff elapsed: exactly ONE request claims the probe slot.
    assert h.allow_request("n1")
    assert h.state("n1") == HALF_OPEN
    assert not h.allow_request("n1")
    h.record_success("n1")
    assert h.state("n1") == CLOSED and not h.is_down("n1")
    assert h.allow_request("n1")


def test_breaker_failed_probe_doubles_backoff(fake_clock):
    h = make_health(fake_clock, breaker_backoff=1.0, breaker_backoff_max=3.0)
    h.record_failure("n1")
    fake_clock.advance(1.0)
    assert h.allow_request("n1")  # probe
    h.record_failure("n1")  # probe failed -> backoff 2.0
    fake_clock.advance(1.0)
    assert not h.allow_request("n1")
    fake_clock.advance(1.0)
    assert h.allow_request("n1")  # next probe at +2.0
    h.record_failure("n1")  # backoff would be 4.0, capped at 3.0
    fake_clock.advance(2.9)
    assert not h.allow_request("n1")
    fake_clock.advance(0.2)
    assert h.allow_request("n1")


def test_breaker_unreported_probe_expires(fake_clock):
    h = make_health(fake_clock, breaker_backoff=1.0, probe_ttl=5.0)
    h.record_failure("n1")
    fake_clock.advance(1.0)
    assert h.allow_request("n1")  # probe claimed, caller dies silently
    fake_clock.advance(5.1)
    # TTL expired: the lost probe counts as failed (backoff doubled to
    # 2.0) and the slot is claimable again after it.
    assert not h.allow_request("n1")
    fake_clock.advance(2.0)
    assert h.allow_request("n1")


def test_probe_due_does_not_claim(fake_clock):
    h = make_health(fake_clock, breaker_backoff=1.0)
    h.record_failure("n1")
    fake_clock.advance(1.0)
    assert h.probe_due("n1")
    assert h.probe_due("n1")  # no side effects
    assert h.allow_request("n1")  # the claim still available


# ------------------------------------------------------------ retry budget


def test_retry_budget_drains_and_refills(fake_clock):
    h = make_health(fake_clock, retry_budget=2.0, retry_refill=0.5)
    assert h.try_spend_retry()
    assert h.try_spend_retry()
    assert not h.try_spend_retry()
    assert h.counters["retries_denied"] == 1
    # Two successes refill one token.
    h.record_success("n1")
    h.record_success("n1")
    assert h.try_spend_retry()
    assert not h.try_spend_retry()


def test_retry_budget_zero_means_unlimited(fake_clock):
    h = make_health(fake_clock, retry_budget=0.0)
    for _ in range(100):
        assert h.try_spend_retry()
    assert h.counters["retries_denied"] == 0


# ----------------------------------------------------------------- hedging


def test_hedge_delay_fixed_and_adaptive(fake_clock):
    h = make_health(fake_clock, hedge_delay=0.2)
    assert h.hedge_delay("n1") == 0.2
    h = make_health(fake_clock, hedge_delay=0.0, hedge_min_delay=0.05)
    assert h.hedge_delay("n1") == 0.05  # no samples -> floor
    for ms in range(1, 101):
        h.record_success("n1", latency=ms / 1000.0)
    # p99 of 1..100ms ~ 0.1s, well above the floor.
    assert 0.09 <= h.hedge_delay("n1") <= 0.1


def test_hedge_volume_cap(fake_clock):
    h = make_health(fake_clock, hedge_max_fraction=0.1)
    for _ in range(100):
        h.record_success("n1")
    fired = sum(1 for _ in range(50) if h.allow_hedge())
    # 10% of 100 requests -> ~10 hedges allowed, the rest suppressed.
    assert fired == 10
    assert h.counters["hedges_suppressed"] == 40
    h2 = make_health(fake_clock, hedge_max_fraction=0.0)
    assert not h2.hedge_enabled()
    assert not h2.allow_hedge()


# ------------------------------------------------------- DownView facade


def test_downview_set_semantics(fake_clock):
    c = Cluster(node=Node(id="n0"),
                nodes=[Node(id="n0"), Node(id="n1"), Node(id="n2")])
    c.health.clock = fake_clock
    assert c.unavailable == set()
    c.mark_unavailable("n1")
    assert "n1" in c.unavailable
    assert set(c.unavailable) == {"n1"}
    assert c.unavailable  # truthy
    c.unavailable.add("n2")
    assert len(c.unavailable) == 2
    c.unavailable.clear()
    assert c.unavailable == set()
    # mark_available is exact: re-marking a healthy node is a no-op.
    c.mark_unavailable("n1")
    c.mark_available("n1")
    assert c.health.state("n1") == CLOSED


def test_remove_node_prunes_health(fake_clock):
    c = Cluster(node=Node(id="n0"), nodes=[Node(id="n0"), Node(id="n1")])
    c.health.clock = fake_clock
    c.mark_unavailable("n1")
    assert "n1" in c.unavailable
    assert c.remove_node("n1")
    # A re-add with the same id must start with a clean breaker.
    assert "n1" not in c.unavailable
    assert c.health.state("n1") == CLOSED
    c.add_node(Node(id="n1"))
    assert "n1" not in c.unavailable


# ------------------------------------------------- executor integration


class CountingClient:
    """query_node double that fails with a given status, counting calls."""

    def __init__(self, status=0):
        self.status = status
        self.calls = 0

    def query_node(self, node, index, query, shards=None, remote=True):
        self.calls += 1
        raise ClientError("boom", status=self.status)


def _exec_fixture(fake_clock, replica_n=1, client=None, **resilience):
    nodes = [Node(id="n0"), Node(id="n1"), Node(id="n2")]
    cluster = Cluster(node=nodes[0], nodes=nodes, replica_n=replica_n,
                      hasher=ModHasher())
    cluster.health.configure(
        ResilienceConfig(**resilience).validate(), clock=fake_clock
    )
    holder = Holder(None)
    holder.open()
    idx = holder.create_index("hx")
    idx.create_field("f")
    client = client or CountingClient()
    ex = Executor(holder, cluster=cluster, client=client, workers=0)
    return ex, cluster, client


def test_executor_zero_connects_while_breaker_open(fake_clock):
    """Acceptance: a blackholed peer costs ZERO connect attempts on the
    query path between half-open probes, and the counters prove it."""
    ex, cluster, client = _exec_fixture(fake_clock, breaker_backoff=2.0)
    remote_shard = next(
        s for s in range(4) if cluster.shard_nodes("hx", s)[0].id == "n1"
    )
    with pytest.raises(PilosaError):
        ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert client.calls == 1
    assert "n1" in cluster.unavailable

    # Steady state: repeated queries never dial the dead peer.
    for _ in range(5):
        with pytest.raises(PilosaError):
            ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert client.calls == 1
    assert cluster.health.counters["breaker_short_circuits"] >= 5

    # Backoff elapses: exactly one query becomes the half-open probe.
    fake_clock.advance(2.0)
    with pytest.raises(PilosaError):
        ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert client.calls == 2
    assert cluster.health.counters["half_open_probes"] == 1
    # The failed probe re-opened with doubled backoff: still no dials.
    with pytest.raises(PilosaError):
        ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert client.calls == 2


def test_executor_retry_budget_bounds_remap(fake_clock):
    """Replica re-map volume stays within the configured budget: once the
    bucket drains, the query fails cleanly instead of walking replicas."""
    ex, cluster, client = _exec_fixture(
        fake_clock, replica_n=2, retry_budget=1.0, retry_refill=0.0
    )
    remote_shard = next(
        s for s in range(8)
        if all(n.id != "n0" for n in cluster.shard_nodes("hx", s))
    )
    # Both owners are remote and failing: the first failure spends the
    # only retry token, the second re-map is denied.
    with pytest.raises(PilosaError, match="retry budget exhausted"):
        ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert client.calls == 2  # primary + the one budgeted retry
    assert cluster.health.counters["retries_denied"] == 1


def test_executor_recovery_recloses_breaker(fake_clock):
    """A peer that comes back is readmitted through one successful
    half-open probe, after which traffic flows normally again."""

    class FlappingClient:
        def __init__(self):
            self.calls = 0
            self.dead = True

        def query_node(self, node, index, query, shards=None, remote=True):
            self.calls += 1
            if self.dead:
                raise ClientError("down", status=0)
            return [len(shards or [])]

    client = FlappingClient()
    ex, cluster, _ = _exec_fixture(fake_clock, client=client,
                                   breaker_backoff=1.0)
    remote_shard = next(
        s for s in range(4) if cluster.shard_nodes("hx", s)[0].id == "n1"
    )
    with pytest.raises(PilosaError):
        ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    client.dead = False
    fake_clock.advance(1.0)
    out = ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert out == [1]
    assert cluster.health.state("n1") == CLOSED
    assert "n1" not in cluster.unavailable
    # Fully readmitted: subsequent queries dial it directly.
    before = client.calls
    ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert client.calls == before + 1


def test_hedged_read_first_good_response_wins(fake_clock):
    """A slow primary triggers a hedge to a replica owning the same shard
    batch; the replica's answer is returned and counted as a hedge win."""
    import threading

    release = threading.Event()

    class SlowPrimaryClient:
        def __init__(self):
            self.targets = []

        def query_node(self, node, index, query, shards=None, remote=True):
            self.targets.append(node.id)
            if node.id == "n1":
                release.wait(5.0)  # primary stuck until the test ends
            return [7]

    nodes = [Node(id="n0"), Node(id="n1"), Node(id="n2")]
    cluster = Cluster(node=nodes[0], nodes=nodes, replica_n=2,
                      hasher=ModHasher())
    cluster.health.configure(
        ResilienceConfig(hedge_delay=0.01, hedge_max_fraction=1.0).validate()
    )
    holder = Holder(None)
    holder.open()
    idx = holder.create_index("hx")
    idx.create_field("f")
    client = SlowPrimaryClient()
    ex = Executor(holder, cluster=cluster, client=client, workers=4)
    try:
        # A shard whose owner set is {n1, n2} (n0 not a replica): primary
        # n1 stalls, the hedge goes to n2.
        shard = next(
            s for s in range(8)
            if {n.id for n in cluster.shard_nodes("hx", s)} == {"n1", "n2"}
        )
        out = ex.execute("hx", "Count(Row(f=1))", shards=[shard])
        assert out == [7]
        assert client.targets[0] == "n1" and "n2" in client.targets
        assert cluster.health.counters["hedges_fired"] == 1
        assert cluster.health.counters["hedges_won"] == 1
    finally:
        release.set()
        ex.close()


def test_half_open_probe_4xx_recloses_breaker(fake_clock):
    """A half-open probe answered with a 4xx proves the peer is
    TRANSPORT-healthy: the breaker must re-close (the app error still
    surfaces), not wedge HALF_OPEN until probe_ttl."""

    class PhaseClient:
        def __init__(self):
            self.status = 0
            self.calls = 0

        def query_node(self, node, index, query, shards=None, remote=True):
            self.calls += 1
            raise ClientError("boom", status=self.status)

    client = PhaseClient()
    ex, cluster, _ = _exec_fixture(fake_clock, client=client,
                                   breaker_backoff=1.0)
    remote_shard = next(
        s for s in range(4) if cluster.shard_nodes("hx", s)[0].id == "n1"
    )
    with pytest.raises(PilosaError):
        ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert cluster.health.state("n1") == OPEN

    client.status = 400  # peer restarted; transport fine, schema lagging
    fake_clock.advance(1.0)
    with pytest.raises(ClientError):  # the 4xx surfaces to the caller
        ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert cluster.health.state("n1") == CLOSED
    # Fully readmitted: the next query dials it again immediately.
    before = client.calls
    with pytest.raises(ClientError):
        ex.execute("hx", "Count(Row(f=1))", shards=[remote_shard])
    assert client.calls == before + 1
