"""Host bitmap unit tests: container ops, set algebra oracle, serialization.

Mirrors the reference's kernel-level strategy (roaring_internal_test.go):
exhaustive container-form coverage (array/bitmap/run) and serialization
round-trips, driven against a plain python-set oracle.
"""

import random

import numpy as np
import pytest

from pilosa_tpu.storage.bitmap import (
    OP_ADD,
    OP_REMOVE,
    Bitmap,
    encode_op,
    parse_op,
)


def random_values(rng, n, span=1 << 22):
    return sorted(rng.sample(range(span), n))


def test_add_remove_contains():
    b = Bitmap()
    assert b.add(100)
    assert not b.add(100)
    assert b.contains(100)
    assert not b.contains(101)
    assert b.add(1 << 40)
    assert b.count() == 2
    assert b.remove(100)
    assert not b.remove(100)
    assert b.count() == 1
    assert b.max() == 1 << 40


def test_add_many_matches_scalar():
    rng = random.Random(1)
    vals = random_values(rng, 5000)
    a, b = Bitmap(), Bitmap()
    for v in vals:
        a.add(v)
    b.add_many(np.array(vals, dtype=np.uint64))
    assert a == b
    assert list(a.slice()) == vals


def test_remove_many():
    vals = list(range(0, 200000, 3))
    b = Bitmap(vals)
    b.remove_many(np.array(vals[::2], dtype=np.uint64))
    assert list(b.slice()) == vals[1::2]


def test_count_range_and_slice_range():
    vals = [0, 1, 65535, 65536, 65537, 1 << 20, (1 << 20) + 5]
    b = Bitmap(vals)
    assert b.count_range(0, 1 << 21) == len(vals)
    assert b.count_range(1, 65537) == 3  # 1, 65535, 65536
    assert list(b.slice_range(65536, (1 << 20) + 1)) == [65536, 65537, 1 << 20]


@pytest.mark.parametrize("seed", range(3))
def test_set_algebra_oracle(seed):
    rng = random.Random(seed)
    # Mix densities so serialization forms array, bitmap and run all occur.
    xs = set(random_values(rng, 3000)) | set(range(70000, 80000))
    ys = set(random_values(rng, 3000)) | set(range(75000, 95000, 2))
    a, b = Bitmap(sorted(xs)), Bitmap(sorted(ys))
    assert set(a.union(b).slice()) == xs | ys
    assert set(a.intersect(b).slice()) == xs & ys
    assert set(a.difference(b).slice()) == xs - ys
    assert set(a.xor(b).slice()) == xs ^ ys
    assert a.intersection_count(b) == len(xs & ys)


def test_flip():
    b = Bitmap([2, 4, 6])
    f = b.flip(1, 6)
    assert list(f.slice()) == [1, 3, 5]
    # Flip is inclusive of end, preserves bits outside range.
    b2 = Bitmap([0, 10])
    f2 = b2.flip(2, 4)
    assert list(f2.slice()) == [0, 2, 3, 4, 10]


def test_offset_range():
    sw = 1 << 20
    b = Bitmap([5, 100, sw + 7, 2 * sw + 9])
    # Extract "row 1" ([sw, 2*sw)) rebased to offset 3*sw.
    out = b.offset_range(3 * sw, sw, 2 * sw)
    assert list(out.slice()) == [3 * sw + 7]


@pytest.mark.parametrize(
    "vals",
    [
        [],
        [0],
        [65535, 65536],
        list(range(1000)),  # run container
        list(range(0, 130000, 2)),  # bitmap container (dense even bits)
        [1 << 48, (1 << 48) + 1],
    ],
)
def test_serialization_roundtrip(vals):
    b = Bitmap(vals)
    data = b.to_bytes()
    b2 = Bitmap.from_bytes(data)
    assert b == b2
    assert list(b2.slice()) == vals


def test_serialization_roundtrip_random_forms():
    rng = random.Random(42)
    vals = (
        random_values(rng, 2000)  # arrays
        + list(range(1 << 17, (1 << 17) + 60000))  # runs
        + list(range(1 << 18, (1 << 18) + 131072, 2))  # bitmaps, 2 containers
    )
    vals = sorted(set(vals))
    b = Bitmap(vals)
    b2 = Bitmap.from_bytes(b.to_bytes())
    assert np.array_equal(b.slice(), b2.slice())


def test_op_log_roundtrip():
    b = Bitmap([1, 2, 3])
    data = b.to_bytes() + encode_op(OP_ADD, 99) + encode_op(OP_REMOVE, 2)
    b2 = Bitmap.from_bytes(data)
    assert list(b2.slice()) == [1, 3, 99]
    assert b2.op_n == 2


def test_op_checksum():
    raw = encode_op(OP_ADD, 12345)
    assert parse_op(raw) == (OP_ADD, 12345)
    corrupted = bytes([raw[0] ^ 1]) + raw[1:]
    with pytest.raises(ValueError):
        parse_op(corrupted)


def test_header_layout():
    # Byte-level check of the fixed header against the reference layout
    # (cookie 12348 LE in bytes 0-3, container count in 4-7).
    b = Bitmap([7])
    data = b.to_bytes()
    assert data[0:2] == (12348).to_bytes(2, "little")
    assert data[2:4] == b"\x00\x00"
    assert int.from_bytes(data[4:8], "little") == 1


# ------------------------- two-form container behavior (round-2 rework) ----


def test_container_densify_and_sparsify():
    from pilosa_tpu.storage.bitmap import ARRAY_MAX_SIZE

    b = Bitmap()
    # Cross the array->bitset threshold via point adds.
    for v in range(ARRAY_MAX_SIZE + 10):
        assert b.add(v)
    c = b.containers[0]
    assert c.bits is not None and c.arr is None
    assert b.count() == ARRAY_MAX_SIZE + 10
    assert b.contains(17) and not b.contains(ARRAY_MAX_SIZE + 10)
    # Remove below the hysteresis point (half the array threshold):
    # converts back to array form.
    for v in range(ARRAY_MAX_SIZE + 10):
        if v % 3:
            assert b.remove(v)
    c = b.containers[0]
    assert c.arr is not None and c.bits is None
    assert b.count() == len([v for v in range(ARRAY_MAX_SIZE + 10) if v % 3 == 0])


def test_dense_bulk_roundtrip_all_forms():
    rng = np.random.default_rng(7)
    vals = rng.choice(1 << 20, size=200_000, replace=False).astype(np.uint64)
    b = Bitmap(vals)
    assert any(c.bits is not None for c in b.containers.values())
    # serialization round trip preserves content regardless of form
    b2 = Bitmap.from_bytes(b.to_bytes())
    assert b == b2
    assert np.array_equal(b.slice(), np.sort(vals))


def test_slice_range_walks_containers_only():
    # values spread over many containers; range covers a partial window
    b = Bitmap()
    b.add_many(np.arange(0, 1 << 22, 13, dtype=np.uint64))
    lo, hi = (1 << 18) + 5, (1 << 21) - 3
    got = b.slice_range(lo, hi)
    all_vals = np.arange(0, 1 << 22, 13, dtype=np.uint64)
    want = all_vals[(all_vals >= lo) & (all_vals < hi)]
    assert np.array_equal(got, want)
    assert b.count_range(lo, hi) == len(want)


def test_range_words_matches_pack_bits():
    from pilosa_tpu.ops.bitplane import pack_bits

    rng = np.random.default_rng(11)
    width = 1 << 17  # two containers
    cols = np.sort(rng.choice(width, size=30_000, replace=False)).astype(np.uint64)
    b = Bitmap(cols)
    words = b.range_words(0, width).view(np.uint32)
    assert np.array_equal(words, pack_bits(cols.astype(np.uint32), width=width))


def test_mixed_form_algebra_matches_oracle():
    rng = np.random.default_rng(3)
    dense = rng.choice(1 << 16, size=30_000, replace=False).astype(np.uint64)
    sparse = rng.choice(1 << 16, size=500, replace=False).astype(np.uint64)
    bd, bs = Bitmap(dense), Bitmap(sparse)
    assert bd.containers[0].bits is not None
    assert bs.containers[0].arr is not None
    sd, ss = set(dense.tolist()), set(sparse.tolist())
    for a, bb, sa, sb in [(bd, bs, sd, ss), (bs, bd, ss, sd)]:
        assert set(a.intersect(bb).slice().tolist()) == sa & sb
        assert set(a.union(bb).slice().tolist()) == sa | sb
        assert set(a.difference(bb).slice().tolist()) == sa - sb
        assert set(a.xor(bb).slice().tolist()) == sa ^ sb
        assert a.intersection_count(bb) == len(sa & sb)


def test_full_container_run_roundtrip():
    # A completely full container serializes as run [0, 65535]; decode must
    # not wrap uint16 at the +1 (would silently drop 65536 bits).
    b = Bitmap(np.arange(1 << 16, dtype=np.uint64))
    b2 = Bitmap.from_bytes(b.to_bytes())
    assert b2.count() == 1 << 16
    assert b == b2


def test_direct_container_assignment_updates_key_cache():
    b = Bitmap(np.arange(0, 1 << 18, 7, dtype=np.uint64))
    _ = b.slice()  # populates the sorted-key cache
    b.containers[1 << 10] = np.array([7], dtype=np.uint16)  # legacy direct set
    assert b.count_range((1 << 10) << 16, ((1 << 10) + 1) << 16) == 1
    assert ((1 << 26) | 7) in set(b.slice().tolist())


def test_lazy_open_detects_corrupt_header_cardinality():
    # The mmap open path trusts the header n at parse time (open stays
    # O(headers)); the first count/mutation touch must recompute and raise
    # (ADVICE r3: a corrupt n silently poisoned Count on the lazy path).
    import struct

    from pilosa_tpu.storage.bitmap import HEADER_BASE_SIZE

    b = Bitmap(np.arange(0, 1 << 16, 2, dtype=np.uint64))  # one dense bitset
    data = bytearray(b.to_bytes())
    n_off = HEADER_BASE_SIZE + 8 + 2  # first container header's n-1 field
    (n_minus_1,) = struct.unpack_from("<H", data, n_off)
    assert n_minus_1 + 1 == 1 << 15
    struct.pack_into("<H", data, n_off, n_minus_1 - 1000)  # corrupt n
    lazy = Bitmap.from_buffer(bytes(data), copy=False)
    with pytest.raises(ValueError, match="corrupt"):
        lazy.count()
    # Eager parse derives n from the payload, so it self-heals.
    assert Bitmap.from_bytes(bytes(data)).count() == 1 << 15


def test_lazy_open_verifies_on_mutation():
    import struct

    from pilosa_tpu.storage.bitmap import HEADER_BASE_SIZE

    b = Bitmap(np.arange(0, 1 << 16, 2, dtype=np.uint64))
    data = bytearray(b.to_bytes())
    n_off = HEADER_BASE_SIZE + 8 + 2
    (n_minus_1,) = struct.unpack_from("<H", data, n_off)
    struct.pack_into("<H", data, n_off, n_minus_1 - 7)
    lazy = Bitmap.from_buffer(bytes(data), copy=False)
    with pytest.raises(ValueError, match="corrupt"):
        lazy.add(1)
    # An uncorrupted lazy open counts fine and settles the flag.
    ok = Bitmap.from_buffer(b.to_bytes(), copy=False)
    assert ok.count() == 1 << 15
    assert ok.count() == 1 << 15  # second count: verified path


def test_corrupt_container_keeps_raising_and_wont_serialize():
    # A caught first error must not silently poison later counts, and
    # to_bytes must refuse to write an internally inconsistent file.
    import struct

    from pilosa_tpu.storage.bitmap import HEADER_BASE_SIZE

    b = Bitmap(np.arange(0, 1 << 16, 2, dtype=np.uint64))
    data = bytearray(b.to_bytes())
    n_off = HEADER_BASE_SIZE + 8 + 2
    (n_minus_1,) = struct.unpack_from("<H", data, n_off)
    struct.pack_into("<H", data, n_off, n_minus_1 - 1000)
    lazy = Bitmap.from_buffer(bytes(data), copy=False)
    for _ in range(2):  # raises EVERY time, not just once
        with pytest.raises(ValueError, match="corrupt"):
            lazy.count()
    with pytest.raises(ValueError, match="corrupt"):
        lazy.to_bytes()
    # copy() must not launder an unverified n either.
    lazy2 = Bitmap.from_buffer(bytes(data), copy=False)
    with pytest.raises(ValueError, match="corrupt"):
        lazy2.clone().count()


# ------------------------------------------------------- run form (in-memory)


def test_run_container_is_compute_form():
    """Runs are a compute+memory form (reference roaring.go:1906-1949
    computes on runs): a contiguous bulk import runifies in memory, ops
    answer from intervals, and a fully-set container costs bytes, not 8 KiB."""
    from pilosa_tpu.storage.bitmap import Container, _as_container

    b = Bitmap()
    b.add_many(np.arange(0, 1 << 16, dtype=np.uint64))  # full container
    c = _as_container(b.containers[0])
    assert c.runs is not None and len(c.runs) == 1
    assert c.runs.nbytes == 4  # vs 8192 for the bitset form
    assert c.n == 1 << 16
    assert b.count() == 1 << 16
    assert b.contains(12345) and not b.contains(1 << 16)
    assert b.count_range(100, 300) == 200
    # Point mutation flattens; a TINY bulk op no longer probes for the
    # run form (the O(n) probe per touch dominated incremental ingest —
    # docs/ingest.md), so re-compression waits for optimize()/snapshot
    # or a chunk that rewrites a meaningful fraction of the container.
    b.remove(500)
    c = _as_container(b.containers[0])
    assert c.runs is None and c.n == (1 << 16) - 1
    b.add_many(np.array([500], dtype=np.uint64))
    c = _as_container(b.containers[0])
    assert c.runs is None and c.n == 1 << 16
    b.optimize()
    c = _as_container(b.containers[0])
    assert c.runs is not None and c.n == 1 << 16


def test_run_intersection_count_all_form_pairs():
    """intersection_count must agree across all 3x3 form combinations."""
    from pilosa_tpu.storage.bitmap import Container

    rng = np.random.default_rng(77)

    def forms(values):
        arr = np.array(sorted(values), dtype=np.uint16)
        a = Container(arr=arr.copy())
        bts = Container(bits=a.as_words().copy())
        r = Container(arr=arr.copy())
        r._maybe_runify()
        if r.runs is None:  # force the run form regardless of heuristics
            from pilosa_tpu.storage.bitmap import _runs_of_array

            r = Container(runs=_runs_of_array(arr))
        return [a, bts, r]

    va = set(range(100, 1000)) | set(rng.integers(0, 1 << 16, 500).tolist())
    vb = set(range(500, 1500)) | set(rng.integers(0, 1 << 16, 500).tolist())
    want = len(va & vb)
    for ca in forms(va):
        for cb in forms(vb):
            assert ca.intersection_count(cb) == want, (ca, cb)


def test_run_container_survives_roundtrip_as_runs():
    b = Bitmap()
    b.add_many(np.arange(1000, 60000, dtype=np.uint64))
    data = b.to_bytes()
    for copy in (True, False):
        rt = Bitmap.from_buffer(data, copy=copy)
        from pilosa_tpu.storage.bitmap import _as_container

        c = _as_container(rt.containers[0])
        assert c.runs is not None, f"copy={copy}"
        assert rt.count() == 59000
        assert rt == b


def test_adversarial_contiguous_import_memory_bounded():
    """1B-bit-scale contiguous range scaled down: every full container must
    hold runs (≈4 B), not bitsets (8 KiB) — the host-memory blowup the
    run form exists to prevent."""
    from pilosa_tpu.storage.bitmap import _as_container

    b = Bitmap()
    n_containers = 64
    b.add_many(np.arange(0, n_containers << 16, dtype=np.uint64))
    payload = sum(
        _as_container(c).runs.nbytes
        for c in b.containers.values()
        if _as_container(c).runs is not None
    )
    runified = sum(
        1 for c in b.containers.values() if _as_container(c).runs is not None
    )
    assert runified == n_containers
    assert payload == 4 * n_containers  # one [start,last] pair each
    assert b.count() == n_containers << 16


def test_run_form_ops_parity_with_oracle():
    """Union/intersect/difference/xor and range reads on run containers
    match the value-set oracle."""
    from pilosa_tpu.storage.bitmap import Container, _runs_of_array

    va = set(range(0, 30000)) | {40000, 40002, 50000}
    vb = set(range(20000, 35000)) | {40002, 60001}
    ca = Container(runs=_runs_of_array(np.array(sorted(va), dtype=np.uint16)))
    cb = Container(runs=_runs_of_array(np.array(sorted(vb), dtype=np.uint16)))
    assert set(ca.union(cb).to_array().tolist()) == va | vb
    assert set(ca.intersect(cb).to_array().tolist()) == va & vb
    assert set(ca.difference(cb).to_array().tolist()) == va - vb
    assert set(ca.xor(cb).to_array().tolist()) == va ^ vb
    assert ca.count_range(100, 25000) == len([v for v in va if 100 <= v < 25000])
    assert list(ca.slice_range(29990, 40003)) == (
        [v for v in sorted(va) if 29990 <= v < 40003]
    )
    assert ca.check("k") == []


def test_fragment_snapshot_optimizes_to_runs(tmp_path):
    """Point-mutation churn leaves flat forms; snapshot() re-compresses
    (reference Optimize at snapshot)."""
    from pilosa_tpu.core.fragment import Fragment
    from pilosa_tpu.storage.bitmap import _as_container

    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
    f.open()
    f.bulk_import(np.zeros(60000, dtype=np.uint64),
                  np.arange(60000, dtype=np.uint64))
    f.clear_bit(0, 123)  # flattens the run container
    f.snapshot()
    c = _as_container(f.storage.containers[0])
    assert c.runs is not None and c.n == 59999
    f.close()
    # And it reopens correctly from the run-encoded file.
    f2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
    f2.open()
    assert f2.row_count(0) == 59999
    f2.close()


def test_corrupt_run_intervals_rejected():
    """A hostile/corrupt run container (inverted or overlapping intervals)
    must fail at parse time, not silently poison count/membership math."""
    import struct

    from pilosa_tpu.storage.bitmap import HEADER_BASE_SIZE

    b = Bitmap()
    b.add_many(np.arange(100, 50000, dtype=np.uint64))  # run-encoded
    data = bytearray(b.to_bytes())
    run_off = HEADER_BASE_SIZE + 12 + 4  # one container: header + offset
    run_n, s0, l0 = struct.unpack_from("<HHH", data, run_off)
    assert run_n == 1 and s0 == 100
    struct.pack_into("<HH", data, run_off + 2, 50000, 100)  # inverted
    with pytest.raises(ValueError, match="corrupt run"):
        Bitmap.from_bytes(bytes(data))
    with pytest.raises(ValueError, match="corrupt run"):
        Bitmap.from_buffer(bytes(data), copy=False)


def test_container_forms_fuzz_against_set_oracle():
    """Randomized op sequences over all three container forms vs a python
    set oracle: point ops, bulk ops, algebra, range reads, serialization
    round trips. Catches form-transition edge cases (runify/flatten/
    densify/sparsify interactions) that targeted tests miss."""
    rng = np.random.default_rng(2024)

    for trial in range(6):
        oracle = set()
        b = Bitmap()
        for step in range(12):
            op = rng.integers(0, 6)
            if op == 0:  # point adds
                vals = rng.integers(0, 1 << 18, rng.integers(1, 50))
                for v in vals:
                    b.add(int(v))
                    oracle.add(int(v))
            elif op == 1:  # point removes
                if oracle:
                    pool = rng.choice(list(oracle), min(len(oracle), 30))
                    for v in pool:
                        b.remove(int(v))
                        oracle.discard(int(v))
            elif op == 2:  # bulk contiguous add (exercises runify)
                start = int(rng.integers(0, 1 << 17))
                width = int(rng.integers(100, 80000))
                vals = np.arange(start, start + width, dtype=np.uint64)
                b.add_many(vals)
                oracle.update(range(start, start + width))
            elif op == 3:  # bulk random add (exercises densify)
                vals = np.unique(rng.integers(0, 1 << 18, 5000)).astype(np.uint64)
                b.add_many(vals)
                oracle.update(int(v) for v in vals)
            elif op == 4:  # bulk remove
                if oracle:
                    pool = np.unique(
                        rng.choice(list(oracle), min(len(oracle), 4000))
                    ).astype(np.uint64)
                    b.remove_many(pool)
                    oracle.difference_update(int(v) for v in pool)
            else:  # serialization round trip (both eager and lazy)
                data = b.to_bytes()
                b = Bitmap.from_buffer(
                    data, copy=bool(rng.integers(0, 2))
                )
            assert b.count() == len(oracle), (trial, step)
            assert b.check() == [], (trial, step, b.check())

        # Final algebra vs oracle against a second random bitmap.
        other_vals = np.unique(np.concatenate([
            rng.integers(0, 1 << 18, 3000),
            np.arange(5000, 45000),  # run-heavy region
        ])).astype(np.uint64)
        other = Bitmap(other_vals)
        other.optimize()
        oset = set(int(v) for v in other_vals)
        assert set(int(v) for v in b.union(other).slice()) == oracle | oset
        assert set(int(v) for v in b.intersect(other).slice()) == oracle & oset
        assert set(int(v) for v in b.difference(other).slice()) == oracle - oset
        assert set(int(v) for v in b.xor(other).slice()) == oracle ^ oset
        assert b.intersection_count(other) == len(oracle & oset)
        # Range reads on the final state.
        lo, hi = 3000, 120000
        assert b.count_range(lo, hi) == len([v for v in oracle if lo <= v < hi])
