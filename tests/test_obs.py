"""Per-query tracing tests (docs/observability.md): recorder units,
cross-node propagation/splicing, trace-shaped chaos assertions (host
rung under an open plane breaker, two dispatch spans across a 409
re-route), the /debug/traces + /metrics HTTP surface, slow-query log,
and the bounded stats histograms that replaced raw timing lists."""

import json
import socket
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import failpoints, obs
from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.cluster.health import ResilienceConfig
from pilosa_tpu.cluster.node import Cluster, Node
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.errors import PilosaError
from pilosa_tpu.executor import Executor
from pilosa_tpu.logger import BufferLogger
from pilosa_tpu.obs import NOP_SPAN, ObsConfig, TraceRecorder
from pilosa_tpu.obs.metrics import render_prometheus
from pilosa_tpu.server.client import ClientError, InternalClient
from pilosa_tpu.server.server import Server
from pilosa_tpu.stats import Histogram, InMemoryStatsClient


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------------------------------------- trace assertions
#
# THE helpers trace-shaped tests go through: pilint R7b validates every
# constant span name passed to them against the real recording sites, so
# a typo'd assertion cannot silently become a no-op test.


def _walk_spans(trace_dict):
    for sp in trace_dict.get("spans", []):
        yield sp
        for ch in sp.get("children", []) or []:
            yield ch


def find_spans(trace_dict, name):
    """Spans (incl. spliced remote children) named exactly `name`."""
    return [sp for sp in _walk_spans(trace_dict) if sp["name"] == name]


def find_span(trace_dict, name):
    spans = find_spans(trace_dict, name)
    assert spans, (
        f"span {name!r} missing from trace; have "
        f"{sorted({s['name'] for s in _walk_spans(trace_dict)})}")
    return spans[0]


def remote_spans(trace_dict):
    return [sp for sp in trace_dict.get("spans", [])
            if sp["name"].startswith("remote:")]


# ------------------------------------------------------------- histograms


def test_histogram_log_buckets_bounded():
    h = Histogram()
    for v in (0.01, 0.5, 3.0, 3.9, 100.0, 1e9):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(0.01 + 0.5 + 3.0 + 3.9 + 100.0 + 1e9)
    assert snap["min"] == 0.01 and snap["max"] == 1e9
    # 3.0 and 3.9 land in the le=4.0 bucket; 1e9 overflows to +Inf.
    assert snap["buckets"][repr(4.0)] == 2
    assert snap["buckets"]["+Inf"] == 1
    # Memory stays O(buckets) no matter how many observations land.
    for _ in range(10000):
        h.observe(1.0)
    assert len(h.buckets) == len(Histogram.BOUNDS) + 1
    assert h.count == 10006


def test_stats_timings_are_bounded_histograms():
    """The old per-key list grew forever (stats.py:91 leak); timings are
    now fixed log-bucketed histograms and snapshot() serves the
    count/sum/buckets shape /metrics needs."""
    s = InMemoryStatsClient()
    for i in range(5000):
        s.timing("QueryMs", float(i % 7))
    snap = s.snapshot()["timings"]["QueryMs"]
    assert snap["count"] == 5000
    assert "buckets" in snap and "sum" in snap
    # Bounded: the histogram object holds buckets, not 5000 floats.
    hist = s.timings["QueryMs"]
    assert len(hist.buckets) == len(Histogram.BOUNDS) + 1


# ----------------------------------------------------------- nop fast path


def test_disabled_span_is_shared_nop_singleton():
    """Disabled-mode fast path: with no active trace, span() returns the
    ONE module-level no-op object — zero allocation per stage site."""
    assert obs.current() is None
    assert obs.span("parse") is NOP_SPAN
    assert obs.span("gather") is NOP_SPAN  # same object every call
    with obs.span("device.dispatch") as sp:
        sp.tag(rung="device")  # all methods are no-ops
    obs.record("reduce", 1.0)  # no trace: silently dropped

    rec = TraceRecorder(ObsConfig(sample_rate=1.0), seed=7)
    t = rec.maybe_start("i", "q")
    token = obs.activate(t)
    try:
        assert obs.span("parse") is not NOP_SPAN
    finally:
        obs.deactivate(token)


def test_sample_rate_zero_starts_nothing():
    rec = TraceRecorder(ObsConfig(sample_rate=0.0))
    assert not rec.enabled
    assert rec.maybe_start("i", "q") is None


# ---------------------------------------------------------------- sampler


def test_sampler_deterministic_under_seed():
    cfg = ObsConfig(sample_rate=0.5)
    a = TraceRecorder(cfg, seed=1234)
    b = TraceRecorder(cfg, seed=1234)
    decisions_a = [a.maybe_start("i", "q") is not None for _ in range(64)]
    decisions_b = [b.maybe_start("i", "q") is not None for _ in range(64)]
    assert decisions_a == decisions_b
    assert any(decisions_a) and not all(decisions_a)
    # Sampled traces get deterministic ids too.
    c = TraceRecorder(cfg, seed=1234)
    ids_a = [t.trace_id for t in
             filter(None, (a.maybe_start("i", "q") for _ in range(64)))]
    ids_c0 = [t.trace_id for t in
              filter(None, (c.maybe_start("i", "q") for _ in range(128)))]
    assert ids_a == ids_c0[len(ids_a):] or ids_a  # ids are non-empty hex
    assert all(len(i) == 16 and int(i, 16) >= 0 for i in ids_a)


# ------------------------------------------------------------------- ring


def test_ring_bounded_newest_first_and_filters():
    rec = TraceRecorder(ObsConfig(sample_rate=1.0, ring_size=4), seed=9)
    for i in range(10):
        t = rec.maybe_start("idx-even" if i % 2 == 0 else "idx-odd", f"q{i}")
        t.record("parse", float(i))
        rec.finish(t)
    out = rec.traces()
    assert len(out) == 4  # ring bound
    assert [o["pql"] for o in out] == ["q9", "q8", "q7", "q6"]  # newest first
    assert all(find_span(o, "parse") for o in out)
    only_even = rec.traces(index="idx-even")
    assert {o["index"] for o in only_even} == {"idx-even"}
    assert len(rec.traces(limit=2)) == 2
    assert rec.snapshot()["traces_finished"] == 10


def test_straggler_span_after_finish_is_dropped():
    """An abandoned hedge leg completing AFTER the winning leg's finish
    must not mutate the published trace: two /debug/traces scrapes of
    one trace id must agree."""
    rec = TraceRecorder(ObsConfig(sample_rate=1.0), seed=4)
    t = rec.maybe_start("i", "q")
    straggler = t.span("remote:slow-peer")
    straggler.__enter__()
    with t.span("remote:fast-peer"):
        pass
    rec.finish(t)
    published = t.to_dict()
    straggler.__exit__(None, None, None)  # hedge loser answers late
    assert t.to_dict()["spans"] == published["spans"]
    assert t.to_dict()["spans_dropped"] == 1
    # Histograms saw only the published span set.
    assert set(rec.stage_histograms()) == {"remote:fast-peer"}


def test_trace_span_cap():
    rec = TraceRecorder(ObsConfig(sample_rate=1.0), seed=3)
    t = rec.maybe_start("i", "q")
    for i in range(600):
        t.record("parse", 0.1)
    rec.finish(t)
    d = t.to_dict()
    assert len(d["spans"]) == 512
    assert d["spans_dropped"] == 88


# ------------------------------------------------------- summary + splice


def test_summary_header_bounded_and_truncating():
    rec = TraceRecorder(ObsConfig(sample_rate=1.0), seed=5)
    t = rec.maybe_start("i", "q")
    for i in range(50):
        t.record("gather", 1.0, kind="cold", n=i)
    rec.finish(t)
    full = t.summary_header(100000)
    assert len(json.loads(full)["spans"]) == 50
    small = t.summary_header(400)
    assert len(small) <= 400
    parsed = json.loads(small)  # still valid JSON after truncation
    assert parsed["truncated"] > 0
    assert parsed["id"] == t.trace_id


def test_splice_valid_oversized_and_garbage():
    rec = TraceRecorder(ObsConfig(sample_rate=1.0), seed=6)
    t = rec.maybe_start("i", "q")
    sp = t.span("remote:peer1")
    with sp:
        pass
    good = json.dumps({"id": "x", "ms": 3.0,
                       "spans": [["gather", 0.1, 2.0, {"kind": "cold"}]]})
    sp.splice(good)
    assert sp.children == [("gather", 0.1, 2.0, {"kind": "cold"})]

    # Oversized peer summary: truncated (tagged), never an error.
    sp2 = t.span("remote:peer2")
    with sp2:
        pass
    sp2.splice("x" * 100000)
    assert sp2.children is None
    assert sp2.tags["summary_truncated"] is True

    # Garbage: dropped with a tag, never an error.
    sp3 = t.span("remote:peer3")
    with sp3:
        pass
    sp3.splice("{not json")
    assert sp3.children is None
    assert "summary_error" in sp3.tags


def test_adopt_header_validation():
    rec = TraceRecorder(ObsConfig(sample_rate=1.0), seed=8)
    t = rec.adopt("deadbeefcafe0123:1", index="i")
    assert t is not None and t.trace_id == "deadbeefcafe0123" and t.adopted
    assert rec.adopt("") is None
    assert rec.adopt("x" * 200) is None  # id too long
    assert rec.adopt("bad id!:1") is None  # junk chars
    assert rec.adopt("abc123:0") is None  # explicit not-sampled flag


# --------------------------------------------------------- slow-query log


def test_slow_query_log_fires_once_with_breakdown(fake_clock):
    log = BufferLogger()
    rec = TraceRecorder(ObsConfig(sample_rate=1.0, slow_query_ms=20.0),
                        logger=log, clock=fake_clock, seed=11)
    fast = rec.maybe_start("i", "Count(Row(f=1))")
    fake_clock.advance(0.005)
    rec.finish(fast)
    assert rec.snapshot()["slow_queries"] == 0
    assert not [l for l in log.lines if "[obs]" in l[1]]

    slow = rec.maybe_start("i", "Count(Row(f=2))")
    token = obs.activate(slow)
    try:
        with obs.span("gather") as sp:
            fake_clock.advance(0.030)
            sp.tag(kind="cold")
    finally:
        obs.deactivate(token)
    rec.finish(slow)
    rec.finish(slow)  # idempotent: logged once
    lines = [l[1] for l in log.lines if "[obs] slow query" in l[1]]
    assert len(lines) == 1
    assert "Count(Row(f=2))" in lines[0]
    assert "gather=30.0ms" in lines[0]
    assert slow.trace_id in lines[0]
    assert rec.snapshot()["slow_queries"] == 1


# ------------------------------------------------------------- prometheus


_PROM_LINE = (
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z0-9_]+="[^"]*"(,[a-zA-Z0-9_]+="[^"]*")*\})? '
    r"[-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|inf|nan)$"
)


def _assert_valid_prometheus(text):
    import re

    families = set()
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            fam = line.split()[2]
            assert fam not in families, f"duplicate TYPE for {fam}"
            families.add(fam)
            continue
        assert re.match(_PROM_LINE, line), f"bad exposition line: {line!r}"
    return families


def test_render_prometheus_shapes():
    h = Histogram()
    for v in (0.5, 3.0, 1e9):
        h.observe(v)
    groups = {
        "scheduler": {"admitted": 7, "waiting": {"interactive": 0},
                      "peers": {"n1": "closed"}},  # strings skipped
        "timings": {"SchedulerWaitMs": h.snapshot()},
        "counters": {"Weird|name:1": 2.5},
        "flags": {"on": True},
    }
    text = render_prometheus(groups, {"parse": h.snapshot()})
    fams = _assert_valid_prometheus(text)
    assert "pilosa_scheduler_admitted" in fams
    assert "pilosa_scheduler_waiting_interactive" in fams
    assert "pilosa_counters_weird_name_1" in fams
    assert "pilosa_timings_schedulerwaitms" in fams
    assert "pilosa_stage_duration_ms" in fams
    # Histogram series are cumulative and end at +Inf == count.
    assert 'pilosa_stage_duration_ms_bucket{stage="parse",le="+Inf"} 3' in text
    assert 'pilosa_stage_duration_ms_count{stage="parse"} 3' in text
    assert "pilosa_flags_on 1" in text
    assert "pilosa_scheduler_peers" not in text  # non-numeric leaf skipped


# ------------------------------------------------------------ HTTP surface


@pytest.fixture
def one_node():
    s = Server(cache_flush_interval=0, member_monitor_interval=0)
    s.open()
    try:
        idx = s.holder.create_index("t")
        fld = idx.create_field("f")
        fld.import_bits(np.zeros(64, dtype=np.uint64),
                        np.arange(64, dtype=np.uint64))
        yield s
    finally:
        s.close()


def _get_json(host, path):
    with urllib.request.urlopen(f"http://{host}{path}") as r:
        return json.load(r)


def test_single_node_trace_surface(one_node):
    h = f"localhost:{one_node.port}"
    c = InternalClient()
    assert c.query(h, "t", "Count(Row(f=0))")["results"] == [64]
    traces = _get_json(h, "/debug/traces")["traces"]
    assert len(traces) == 1
    tr = traces[0]
    assert tr["index"] == "t" and tr["pql"] == "Count(Row(f=0))"
    assert tr["status"] == "ok" and tr["duration_ms"] > 0
    for name in ("parse", "sched.wait", "batch.hold", "gather",
                 "device.dispatch", "executor.fanout", "reduce"):
        find_span(tr, name)
    assert find_span(tr, "gather")["tags"]["kind"] == "cold"
    assert find_span(tr, "device.dispatch")["tags"]["rung"] == "device"
    # min-ms filter: an impossible threshold returns nothing.
    assert _get_json(h, "/debug/traces?min-ms=1e9")["traces"] == []
    # /debug/vars obs group.
    dv = _get_json(h, "/debug/vars")
    assert dv["obs"]["traces_finished"] == 1
    # /metrics: valid exposition covering existing groups + stage hists.
    with urllib.request.urlopen(f"http://{h}/metrics") as r:
        assert "text/plain" in r.headers["Content-Type"]
        text = r.read().decode()
    fams = _assert_valid_prometheus(text)
    assert "pilosa_scheduler_admitted" in fams
    assert "pilosa_engine_cache_count_dispatches" in fams
    assert "pilosa_obs_traces_finished" in fams
    assert 'stage="parse"' in text and 'stage="gather"' in text


def test_client_stamped_header_cannot_force_tracing(one_node):
    """Adoption is for coordinator-forwarded (remote=true) sub-queries
    only: an ordinary client stamping X-Pilosa-Trace must not bypass the
    sampler (with sample-rate 0 it would force span recording, ring
    retention of attacker PQL, and slow-query log lines the operator
    turned off)."""
    one_node.trace_recorder.config.sample_rate = 0.0
    h = f"localhost:{one_node.port}"
    req = urllib.request.Request(
        f"http://{h}/index/t/query", data=b"Count(Row(f=0))",
        headers={"X-Pilosa-Trace": "deadbeefcafe0123:1"}, method="POST")
    with urllib.request.urlopen(req) as r:
        assert json.load(r)["results"] == [64]
    dv = _get_json(h, "/debug/vars")["obs"]
    assert dv["traces_adopted"] == 0 and dv["traces_started"] == 0
    assert _get_json(h, "/debug/traces")["traces"] == []


def test_debug_traces_bad_params_are_400(one_node):
    h = f"localhost:{one_node.port}"
    for qs in ("min-ms=abc", "limit=xyz"):
        try:
            urllib.request.urlopen(f"http://{h}/debug/traces?{qs}")
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 400, (qs, e.code)


def test_sampling_disabled_serves_untraced():
    s = Server(cache_flush_interval=0, member_monitor_interval=0,
               obs_config=ObsConfig(sample_rate=0.0))
    s.open()
    try:
        idx = s.holder.create_index("t")
        idx.create_field("f").import_bits(
            np.zeros(8, dtype=np.uint64), np.arange(8, dtype=np.uint64))
        h = f"localhost:{s.port}"
        c = InternalClient()
        assert c.query(h, "t", "Count(Row(f=0))")["results"] == [8]
        assert _get_json(h, "/debug/traces")["traces"] == []
        assert _get_json(h, "/debug/vars")["obs"]["traces_started"] == 0
    finally:
        s.close()


# -------------------------------------------------- cross-node (3 nodes)


@pytest.fixture
def cluster3(tmp_path):
    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        s = Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=port,
            cluster_hosts=hosts,
            replica_n=1,
            hasher=ModHasher(),
            cache_flush_interval=0,
            anti_entropy_interval=0,
            executor_workers=0,
        )
        s.open()
        servers.append(s)
    yield servers
    for s in servers:
        s.close()


def test_three_node_fanout_single_trace_tree(cluster3):
    """THE acceptance trace: a fan-out Count over 3 nodes yields ONE
    tree on the coordinator — local stage spans plus a remote:<peer>
    span per hop whose children are the peer's own spans, spliced from
    the size-bounded summary header (offsets relative to the hop, so
    peer clock skew cannot corrupt the tree)."""
    c = InternalClient()
    h0 = f"localhost:{cluster3[0].port}"
    c.create_index(h0, "t")
    c.create_field(h0, "t", "f")
    time.sleep(0.05)
    # One bit per shard 0..2: with ModHasher the three shards spread
    # across the three nodes, so the Count must fan out.
    c.import_bits(h0, "t", "f", [(1, s * SHARD_WIDTH + 5) for s in range(3)])
    time.sleep(0.05)
    assert c.query(h0, "t", "Count(Row(f=1))")["results"] == [3]

    traces = _get_json(h0, "/debug/traces?index=t")["traces"]
    tree = next(t for t in traces if remote_spans(t)
                and t["pql"] == "Count(Row(f=1))")
    # Coordinator stages.
    for name in ("parse", "sched.wait", "executor.fanout", "reduce"):
        find_span(tree, name)
    # Remote hops: at least one peer served shards, each hop carries the
    # peer's spliced sub-spans (the peer ran the device path).
    hops = remote_spans(tree)
    assert hops, tree
    for hop in hops:
        child_names = {ch["name"] for ch in hop.get("children", [])}
        assert "parse" in child_names, hop
        assert "device.dispatch" in child_names, hop
        assert "gather" in child_names, hop
    # The whole tree covers every acceptance stage.
    all_names = {sp["name"] for sp in _walk_spans(tree)}
    for name in ("parse", "sched.wait", "batch.hold", "gather",
                 "device.dispatch", "reduce"):
        assert name in all_names, (name, sorted(all_names))

    # Peer rings hold the ADOPTED twin under the same trace id: one
    # logical trace across nodes.
    tid = tree["id"]
    adopted = []
    for s in cluster3[1:]:
        hp = f"localhost:{s.port}"
        adopted += [t for t in _get_json(hp, "/debug/traces")["traces"]
                    if t["id"] == tid]
    assert adopted, "no peer recorded the forwarded trace id"


# ------------------------------------------- trace-shaped chaos assertions


def test_breaker_open_trace_shows_host_rung(tmp_path):
    """DEGRADE-shaped: once the plane breaker opens, a served query's
    trace must show the HOST rung — the evidence that degraded serving
    took the ladder, not the device."""
    s = Server(
        data_dir=str(tmp_path / "n0"), cache_flush_interval=0,
        member_monitor_interval=0,
        resilience_config=ResilienceConfig(
            device_breaker_failures=1, device_breaker_backoff=60.0),
    )
    s.open()
    try:
        idx = s.holder.create_index("t")
        idx.create_field("f").import_bits(
            np.zeros(32, dtype=np.uint64), np.arange(32, dtype=np.uint64))
        h = f"localhost:{s.port}"
        c = InternalClient()
        failpoints.configure("device-dispatch", "error")
        try:
            # Opens the plane breaker; the request itself serves one rung
            # down (host) in-flight.
            assert c.query(h, "t", "Count(Row(f=0))")["results"] == [32]
            # Routed to host BEFORE any dispatch now.
            assert c.query(h, "t", "Count(Row(f=0))")["results"] == [32]
        finally:
            failpoints.reset()
        traces = _get_json(h, "/debug/traces")["traces"]
        routed = traces[0]  # newest: the breaker-open query
        dispatches = find_spans(routed, "device.dispatch")
        assert dispatches and all(
            d["tags"]["rung"] == "host" for d in dispatches), routed
        # The first (fallback) trace shows BOTH rungs: the failed device
        # attempt and the host rung that answered.
        fallback = traces[1]
        rungs = {d["tags"]["rung"]
                 for d in find_spans(fallback, "device.dispatch")}
        assert rungs == {"device", "host"}, fallback
    finally:
        s.close()


def test_409_reroute_trace_shows_two_dispatch_spans(fake_clock):
    """FAULT/rebalance-shaped: a routing-conflict 409 re-route must leave
    TWO dispatch spans in the trace — the refused hop and the re-routed
    one — so an operator can see the re-route happened and what it cost."""

    class RerouteClient:
        def __init__(self):
            self.calls = []

        def query_node(self, node, index, query, shards=None, remote=True,
                       **kw):
            self.calls.append(node.id)
            if len(self.calls) == 1:
                raise ClientError("shard moved", status=409)
            return [len(shards or [])]

    nodes = [Node(id="n0"), Node(id="n1"), Node(id="n2")]
    cluster = Cluster(node=nodes[0], nodes=nodes, replica_n=2,
                      hasher=ModHasher())
    cluster.health.configure(ResilienceConfig().validate(), clock=fake_clock)
    holder = Holder(None)
    holder.open()
    holder.create_index("hx").create_field("f")
    client = RerouteClient()
    ex = Executor(holder, cluster=cluster, client=client, workers=0)
    # A shard owned by n1+n2 (never n0) so the dispatch is remote.
    shard = next(
        s for s in range(8)
        if not any(n.id == "n0" for n in cluster.shard_nodes("hx", s)))

    rec = TraceRecorder(ObsConfig(sample_rate=1.0), seed=13)
    trace = rec.maybe_start("hx", "Count(Row(f=1))")
    token = obs.activate(trace)
    try:
        ex.execute("hx", "Count(Row(f=1))", shards=[shard])
    finally:
        obs.deactivate(token)
        rec.finish(trace)
    assert len(client.calls) == 2 and client.calls[0] != client.calls[1]
    tree = trace.to_dict()
    hops = remote_spans(tree)
    assert len(hops) == 2, tree
    # First hop carries the routing-conflict error tag; second answered.
    assert hops[0]["tags"].get("error") == "ClientError", hops
    assert "error" not in (hops[1].get("tags") or {}), hops


# ------------------------------------------------------------ config knobs


def test_obs_config_toml_env_flag_precedence(tmp_path, monkeypatch):
    from pilosa_tpu.config import Config

    p = tmp_path / "c.toml"
    p.write_text("[obs]\nsample-rate = 0.25\nring-size = 32\n"
                 "slow-query-ms = 15.0\n")
    cfg = Config.load(str(p))
    assert cfg.obs.sample_rate == 0.25
    assert cfg.obs.ring_size == 32
    assert cfg.obs.slow_query_ms == 15.0
    monkeypatch.setenv("PILOSA_TPU_OBS_SAMPLE_RATE", "0.5")
    cfg = Config.load(str(p))
    assert cfg.obs.sample_rate == 0.5  # env beats file
    cfg = Config.load(str(p), flags={"obs_sample_rate": 1.0,
                                     "obs_ring_size": 8})
    assert cfg.obs.sample_rate == 1.0 and cfg.obs.ring_size == 8
    # Round-trips through to_toml (env cleared: it would rightly win).
    monkeypatch.delenv("PILOSA_TPU_OBS_SAMPLE_RATE")
    (tmp_path / "dump.toml").write_text(cfg.to_toml())
    cfg2 = Config.load(str(tmp_path / "dump.toml"))
    assert cfg2.obs.sample_rate == 1.0 and cfg2.obs.ring_size == 8
    # Validation rejects nonsense at build time.
    with pytest.raises(ValueError):
        ObsConfig(sample_rate=2.0).validate()
    with pytest.raises(ValueError):
        ObsConfig(ring_size=-1).validate()
    with pytest.raises(ValueError):
        ObsConfig(slow_query_ms=-1.0).validate()
