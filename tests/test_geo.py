"""Geo replication: follower clusters tailing CDC, bounded-staleness
reads, fenced leader-loss promotion (docs/geo-replication.md).

The contract under test: a follower cluster converges to byte-identical
fragments through the idempotent anti-entropy merge; its cursor is
durable (apply-then-checkpoint — a kill between the two re-applies
idempotently, never loses an acked record); reads under
X-Pilosa-Max-Staleness are served locally within the lag bound and
409 with lag/bound/position beyond it (clean no-op on a non-geo node);
promotion bumps a fencing geo epoch whose handshake makes it
impossible for two clusters to accept writes under the same epoch, and
an aborted promotion fully reverts.
"""

import json
import random
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.cdc import CdcConfig
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.errors import PilosaError, StaleGeoEpochError, StaleReadError
from pilosa_tpu.failpoints import InjectedFault
from pilosa_tpu.geo import GeoConfig
from pilosa_tpu.server.server import Server


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_leader(tmp_path, name="leader"):
    s = Server(data_dir=str(tmp_path / name), cache_flush_interval=0,
               executor_workers=0,
               cdc_config=CdcConfig(enabled=True),
               geo_config=GeoConfig(role="leader"))
    s.open()
    return s


def make_follower(tmp_path, leader_host, name="follower", **geo_kw):
    geo_kw.setdefault("backoff", 0.05)
    s = Server(data_dir=str(tmp_path / name), cache_flush_interval=0,
               executor_workers=0,
               cdc_config=CdcConfig(enabled=True),
               geo_config=GeoConfig(role="follower", leader=leader_host,
                                    **geo_kw))
    s.open()
    return s


def wait_until(fn, timeout=20.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return
        except Exception:
            pass
        time.sleep(interval)
    assert fn(), f"timed out waiting for {msg}"


def frag_bytes(s, index="i", field="f", shard=0):
    frag = s.holder.fragment(index, field, "standard", shard)
    assert frag is not None
    frag.snapshot()  # quiesce background WAL splicing before comparing
    return frag.storage.to_bytes()


def count_row(s, row=1, index="i", field="f"):
    return s.api.query(index, f"Count(Row({field}={row}))")[0]


def _post_query(port, index, query, headers=None, timeout=30):
    req = urllib.request.Request(
        f"http://localhost:{port}/index/{index}/query",
        data=query.encode(), headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


@pytest.fixture
def pair(tmp_path):
    """A converging leader/follower pair with index `i`, field `f`
    created BEFORE the follower opens (its first schema sync links it)."""
    leader = make_leader(tmp_path)
    leader.api.create_index("i")
    leader.api.create_field("i", "f")
    follower = make_follower(tmp_path, f"localhost:{leader.port}")
    servers = [leader, follower]
    try:
        yield leader, follower
    finally:
        failpoints.reset()
        for s in reversed(servers):
            try:
                s.close()
            except Exception:
                pass


# ------------------------------------------------------------ convergence


def test_tail_apply_convergence_byte_identical(pair):
    """A Set/Clear mix across two shards converges byte-for-byte through
    the stream path alone — no bootstrap, cursor checkpoints on disk."""
    leader, follower = pair
    rng = random.Random(7)
    for _ in range(60):
        col = rng.randrange(40)
        shard = rng.randrange(2)
        col += shard * SHARD_WIDTH
        if rng.random() < 0.3:
            leader.api.query("i", f"Clear({col}, f=1)")
        else:
            leader.api.query("i", f"Set({col}, f=1)")
    want = count_row(leader)
    wait_until(lambda: count_row(follower) == want, msg="follower count")
    for shard in (0, 1):
        assert frag_bytes(follower, shard=shard) == \
            frag_bytes(leader, shard=shard)
    snap = follower.geo.tailer.snapshot()
    # Every CDC record the leader assigned was applied, exactly once per
    # position (no-op writes assign no position, so equality is exact).
    assert snap["records_applied"] == leader.cdc.log("i").last_pos
    assert snap["bootstraps"] == 0  # pure stream path
    assert snap["checkpoints"] >= 1
    assert follower.geo.lag() < 30.0  # finite: head reached, stamps flowed


def test_durable_cursor_across_restart(pair, tmp_path):
    """Close the follower, keep writing, reopen from the same data dir:
    it resumes from the checkpointed cursor (no 410 re-seed) and
    converges loss-free."""
    leader, follower = pair
    for col in range(20):
        leader.api.query("i", f"Set({col}, f=1)")
    wait_until(lambda: count_row(follower) == 20, msg="initial converge")
    follower.close()
    for col in range(20, 40):
        leader.api.query("i", f"Set({col}, f=1)")
    follower2 = make_follower(tmp_path, f"localhost:{leader.port}",
                              name="follower")
    try:
        wait_until(lambda: count_row(follower2) == 40, msg="re-converge")
        assert frag_bytes(follower2) == frag_bytes(leader)
        snap = follower2.geo.tailer.snapshot()
        # The cursor survived: this life streamed the tail, never 410'd
        # into a base re-pull, and never re-applied the first window.
        assert snap["bootstraps"] == 0
        assert snap["records_applied"] <= 20
    finally:
        follower2.close()


def test_apply_fault_cursor_holds_then_idempotent_replay(pair):
    """A mid-chunk apply fault leaves the cursor where it was (never
    advanced over un-applied state); the retry re-applies the window
    idempotently and still converges byte-identical — the SIGKILL-
    between-apply-and-checkpoint story, driven by the failpoint."""
    leader, follower = pair
    for col in range(10):
        leader.api.query("i", f"Set({col}, f=1)")
    wait_until(lambda: count_row(follower) == 10, msg="baseline")
    failpoints.configure("geo-apply", "error", count=1)
    leader.api.query("i", "Clear(3, f=1)")
    leader.api.query("i", "Set(11, f=1)")
    leader.api.query("i", "Set(12, f=1)")
    wait_until(lambda: failpoints.hits("geo-apply") >= 1, msg="fault fired")
    wait_until(lambda: count_row(follower) == 11, msg="post-fault converge")
    assert follower.geo.tailer.counters["apply_errors"] >= 1
    assert frag_bytes(follower) == frag_bytes(leader)


def test_bootstrap_on_incarnation_change(pair):
    """Recreating the index on the leader flips the CDC incarnation: the
    follower's stale-life cursor 410s into a base-image bootstrap and
    converges to the new life's bytes."""
    leader, follower = pair
    for col in range(8):
        leader.api.query("i", f"Set({col}, f=1)")
    wait_until(lambda: count_row(follower) == 8, msg="first life")
    leader.api.delete_index("i")
    leader.api.create_index("i")
    leader.api.create_field("i", "f")
    leader.api.query("i", "Set(99, f=1)")
    wait_until(lambda: follower.geo.tailer.counters["bootstraps"] >= 1,
               msg="bootstrap")
    wait_until(lambda: count_row(follower) == 1, msg="second life")
    assert frag_bytes(follower) == frag_bytes(leader)


def test_bootstrap_clears_divergent_fragments(pair):
    """Bootstrap REPLACES local state with the leader's view — including
    fragments the response does NOT carry. Data from the old index life
    that the new leader never wrote (here: a shard-1 fragment) must be
    cleared by the re-seed, not served forever."""
    leader, follower = pair
    leader.api.query("i", f"Set({SHARD_WIDTH + 2}, f=1)")  # shard 1
    leader.api.query("i", "Set(1, f=1)")                   # shard 0
    wait_until(lambda: count_row(follower) == 2, msg="first life")
    leader.api.delete_index("i")
    leader.api.create_index("i")
    leader.api.create_field("i", "f")
    leader.api.query("i", "Set(2, f=1)")  # shard 0 only in the new life
    wait_until(lambda: follower.geo.tailer.counters["bootstraps"] >= 1,
               msg="bootstrap")
    # Without divergence clearing the stale shard-1 bit lingers and the
    # count stays 2 forever.
    wait_until(lambda: count_row(follower) == 1, msg="second life")
    assert follower.geo.tailer.counters["bootstrap_cleared"] >= 1
    frag = follower.holder.fragment("i", "f", "standard", 1)
    assert frag is None or frag.storage.count() == 0
    assert frag_bytes(follower) == frag_bytes(leader)


def test_checkpoint_implies_synced_wal(pair):
    """The cursor checkpoint durably claims its chunk's positions, so
    the fragment WAL tails it covers must be fsynced first. Under the
    default fsync=batch policy the applied records would otherwise sit
    in the page cache (batch threshold not reached) while the cursor
    file is already durably replaced — a crash in that window loses a
    tail the cursor says was applied, a gap never re-fetched."""
    leader, follower = pair
    for col in range(10):
        leader.api.query("i", f"Set({col}, f=1)")
    wait_until(lambda: count_row(follower) == 10, msg="converge")

    def synced():
        frag = follower.holder.fragment("i", "f", "standard", 0)
        return frag is not None \
            and frag.storage_config.fsync == "batch" \
            and follower.geo.tailer.counters["checkpoints"] >= 1 \
            and frag._unsynced_ops == 0
    # 10 applied ops < fsync_batch_ops=64: without the pre-checkpoint
    # wal_sync the counter would sit at 10 indefinitely.
    wait_until(synced, msg="WAL synced before checkpoint")


# ------------------------------------------------------ staleness contract


def test_staleness_409_payload_and_local_serve(pair):
    leader, follower = pair
    leader.api.query("i", "Set(1, f=1)")
    wait_until(lambda: count_row(follower) == 1, msg="converge")
    # Within bound: answered locally.
    st, body = _post_query(follower.port, "i", "Count(Row(f=1))",
                           headers={"X-Pilosa-Max-Staleness": "30"})
    assert st == 200 and body["results"][0] == 1
    # A zero bound can never be satisfied (lag includes time since the
    # last leader contact): typed 409 carrying the current lag.
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_query(follower.port, "i", "Count(Row(f=1))",
                    headers={"X-Pilosa-Max-Staleness": "0"})
    assert ei.value.code == 409
    body = json.loads(ei.value.read())
    assert body["bound"] == 0.0
    assert body["lag"] is None or body["lag"] >= 0.0
    assert isinstance(body["position"], int)
    assert "staleness" in body["error"]
    # Same contract through the in-process API.
    with pytest.raises(StaleReadError) as se:
        follower.api.query("i", "Count(Row(f=1))", max_staleness=0.0)
    assert se.value.bound == 0.0
    # Malformed header is a 400, not a silent fresh read.
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_query(follower.port, "i", "Count(Row(f=1))",
                    headers={"X-Pilosa-Max-Staleness": "soon"})
    assert ei.value.code == 400


def test_max_staleness_noop_on_non_geo_node(tmp_path):
    """On a node with no geo role the header is a documented clean
    no-op: the read executes normally (it IS fresh here) even with a
    bound no follower could meet."""
    s = Server(data_dir=str(tmp_path / "plain"), cache_flush_interval=0,
               executor_workers=0)
    s.open()
    try:
        assert s.geo is None
        s.api.create_index("i")
        s.api.create_field("i", "f")
        s.api.query("i", "Set(1, f=1)")
        for bound in ("30", "0"):
            st, body = _post_query(s.port, "i", "Count(Row(f=1))",
                                   headers={"X-Pilosa-Max-Staleness": bound})
            assert st == 200 and body["results"][0] == 1
        assert s.api.query("i", "Count(Row(f=1))", max_staleness=0.0)[0] == 1
    finally:
        s.close()


# --------------------------------------------------- promotion and fencing


def test_follower_refuses_writes_typed_409(pair):
    leader, follower = pair
    wait_until(lambda: follower.holder.index("i") is not None, msg="schema")
    with pytest.raises(StaleGeoEpochError):
        follower.api.query("i", "Set(1, f=1)")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_query(follower.port, "i", "Set(2, f=1)")
    assert ei.value.code == 409
    body = json.loads(ei.value.read())
    assert body["current"] == 0 and "epoch" in body["error"]
    assert follower.geo.counters["writes_refused"] >= 2


def test_promote_abort_fully_reverts(pair):
    """A failure inside promotion (before the durable persist) reverts
    everything: role, epoch, and the tail loop — then a clean promote
    succeeds."""
    leader, follower = pair
    leader.api.query("i", "Set(1, f=1)")
    wait_until(lambda: count_row(follower) == 1, msg="converge")
    failpoints.configure("geo-promote", "error", count=1)
    with pytest.raises(InjectedFault):
        follower.geo.promote()
    st = follower.geo.status()
    assert st["role"] == "follower" and st["epoch"] == 0
    assert follower.geo.counters["promote_aborts"] == 1
    # Tailing resumed as if nothing happened.
    leader.api.query("i", "Set(2, f=1)")
    wait_until(lambda: count_row(follower) == 2, msg="tail resumed")
    st = follower.geo.promote()
    assert st["role"] == "leader" and st["epoch"] == 1


def test_promote_fence_demote_rejoin(pair):
    """Operator promotion over HTTP: the follower bumps the geo epoch,
    the fence demotes the old leader (which refuses writes with a typed
    409, adopts the epoch, and re-tails the new leader through a fresh
    bootstrap), and a stale demote is refused — authority flows only
    forward."""
    leader, follower = pair
    for col in range(10):
        leader.api.query("i", f"Set({col}, f=1)")
    wait_until(lambda: count_row(follower) == 10, msg="converge")
    req = urllib.request.Request(
        f"http://localhost:{follower.port}/geo/promote", data=b"")
    with urllib.request.urlopen(req, timeout=30) as r:
        st = json.loads(r.read())
    assert st["role"] == "leader" and st["epoch"] == 1
    # The fence lands: old leader demotes and adopts the epoch verbatim.
    wait_until(lambda: leader.geo.status()["role"] == "follower",
               msg="fence demotes old leader")
    assert leader.geo.status()["epoch"] == 1
    # Writes at the deposed leader: typed 409.
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_query(leader.port, "i", "Set(50, f=1)")
    assert ei.value.code == 409
    assert json.loads(ei.value.read())["current"] == 1
    # New leader accepts; the old leader re-tails it (cursors were
    # wiped, so it replays the new leader's feed from position zero —
    # idempotent over the bits it already holds).
    follower.api.query("i", "Set(11, f=1)")
    wait_until(lambda: count_row(leader) == 11, msg="old leader re-tails")
    assert frag_bytes(leader) == frag_bytes(follower)
    assert leader.geo.tailer.counters["records_applied"] >= 11
    # Stale handshake refused: epoch must be strictly greater.
    with pytest.raises(StaleGeoEpochError):
        leader.geo.demote(leader=f"localhost:{follower.port}", epoch=1)
    assert leader.geo.counters["demotions_refused"] >= 1
    # /geo/status and the geo /debug/vars group carry the state.
    with urllib.request.urlopen(
            f"http://localhost:{leader.port}/geo/status", timeout=30) as r:
        assert json.loads(r.read())["role"] == "follower"
    with urllib.request.urlopen(
            f"http://localhost:{follower.port}/debug/vars", timeout=30) as r:
        dv = json.loads(r.read())["geo"]
    assert dv["role"] == "leader" and dv["epoch"] == 1
    assert dv["promotions"] == 1 and "tail" in dv


def test_probe_driven_promotion(tmp_path):
    """With probe-promote on, sustained leader-contact failure promotes
    the follower from the tail thread itself."""
    leader = make_leader(tmp_path)
    leader.api.create_index("i")
    leader.api.create_field("i", "f")
    follower = make_follower(tmp_path, f"localhost:{leader.port}",
                             backoff=0.05, backoff_max=0.1,
                             probe_promote=True, probe_failures=3)
    try:
        wait_until(lambda: follower.holder.index("i") is not None,
                   msg="schema")
        leader.close()
        wait_until(lambda: follower.geo.status()["role"] == "leader",
                   timeout=30, msg="probe promotion")
        assert follower.geo.status()["epoch"] == 1
        assert follower.geo.counters["probe_promotions"] == 1
    finally:
        try:
            follower.close()
        finally:
            try:
                leader.close()
            except Exception:
                pass


@pytest.mark.chaos
def test_geo_chaos_fencing_no_shared_epoch(pair):
    """Seed-pinned chaos: writers hammer BOTH clusters through a
    promotion + fence + rejoin while the tail path runs under a flaky
    failpoint. The fencing invariant: no write is ever accepted by two
    clusters under the same geo epoch (accepted-epoch sets stay
    disjoint), and every refused write is a typed 409 — correct answers
    and typed errors are the only outcomes."""
    leader, follower = pair
    wait_until(lambda: follower.holder.index("i") is not None, msg="schema")
    failpoints.seed(4242)
    failpoints.configure("geo-tail", "flaky", arg=0.3)
    stop = threading.Event()
    outcomes = {"ok": 0, "fenced": 0, "other": []}
    lock = threading.Lock()

    def writer(port, seed):
        rng = random.Random(seed)
        while not stop.is_set():
            col = rng.randrange(200)
            try:
                _post_query(port, "i", f"Set({col}, f=1)", timeout=10)
                with lock:
                    outcomes["ok"] += 1
            except urllib.error.HTTPError as e:
                with lock:
                    if e.code == 409:
                        outcomes["fenced"] += 1
                    else:
                        outcomes["other"].append(e.code)
            except Exception as e:  # noqa: BLE001 - tallied and asserted
                with lock:
                    outcomes["other"].append(repr(e))
            time.sleep(0.002)

    threads = [
        threading.Thread(target=writer, args=(leader.port, 1)),
        threading.Thread(target=writer, args=(follower.port, 2)),
    ]
    for t in threads:
        t.start()
    try:
        time.sleep(0.3)
        follower.geo.promote()
        wait_until(lambda: leader.geo.status()["role"] == "follower",
                   timeout=30, msg="fence lands")
        time.sleep(0.5)  # both sides keep taking traffic post-fence
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        failpoints.reset()
    assert outcomes["other"] == [], outcomes
    assert outcomes["ok"] > 0 and outcomes["fenced"] > 0, outcomes
    # THE invariant: the two clusters' accepted-write epochs are
    # disjoint — split-brain writes cannot hide under a shared epoch.
    a = {k for k, v in leader.geo.write_epochs.items() if v}
    b = {k for k, v in follower.geo.write_epochs.items() if v}
    assert a and b, (a, b)
    assert not (a & b), (a, b)
    assert a == {0} and b == {1}, (a, b)
    # Epoch-0 writes acked by the old leader inside the promotion window
    # (after the follower's tail paused, before the fence landed) never
    # reached the new leader's feed — that divergence is the documented
    # failover cost. The re-tailed old leader must still apply
    # EVERYTHING the new leader serves: its row converges to a superset.
    want = set(int(c) for c in
               follower.api.query("i", "Row(f=1)")[0].columns())
    wait_until(
        lambda: want <= set(int(c) for c in
                            leader.api.query("i", "Row(f=1)")[0].columns()),
        msg="post-chaos superset converge")


# ------------------------------------------------------------ config knobs


def test_geo_config_sources(tmp_path, monkeypatch):
    from pilosa_tpu.config import Config

    toml = tmp_path / "c.toml"
    toml.write_text('[geo]\nrole = "follower"\nleader = "h:1"\n'
                    'backoff-max = 12.5\n')
    cfg = Config.load(str(toml))
    assert cfg.geo.role == "follower" and cfg.geo.leader == "h:1"
    assert cfg.geo.backoff_max == 12.5
    monkeypatch.setenv("PILOSA_TPU_GEO_BACKOFF", "0.25")
    cfg = Config.load(str(toml))
    assert cfg.geo.backoff == 0.25  # env beats file
    cfg = Config.load(str(toml), flags={"geo_probe_failures": 3,
                                        "geo_probe_promote": 1})
    assert cfg.geo.probe_failures == 3
    assert cfg.geo.validate().probe_promote is True  # coerced to bool
    assert "[geo]" in cfg.to_toml()
    with pytest.raises(ValueError):
        GeoConfig(role="follower").validate()  # leader required
    with pytest.raises(ValueError):
        GeoConfig(role="primary").validate()
    with pytest.raises(ValueError):
        GeoConfig(backoff=0.0).validate()


def test_geo_disabled_operator_surface(tmp_path):
    """Geo endpoints on a non-geo node: typed 400, not a crash."""
    s = Server(data_dir=str(tmp_path / "plain"), cache_flush_interval=0,
               executor_workers=0)
    s.open()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            req = urllib.request.Request(
                f"http://localhost:{s.port}/geo/promote", data=b"")
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
        assert "geo" in json.loads(ei.value.read())["error"]
    finally:
        s.close()
