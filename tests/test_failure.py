"""Failure detection + query-time replica retry.

Model: reference executor.go:1498-1508 (mapReduce retry on replicas) and
memberlist gossip failure surfacing. A 3-node replica_n=2 cluster keeps
answering full queries after one node dies.
"""

import socket
import time

import pytest

from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.errors import PilosaError
from pilosa_tpu.server.client import ClientError, InternalClient
from pilosa_tpu.server.server import Server


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def cluster3r(tmp_path):
    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        s = Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=port,
            cluster_hosts=hosts,
            replica_n=2,
            hasher=ModHasher(),
            cache_flush_interval=0,
            anti_entropy_interval=0,
            member_monitor_interval=0,  # tests trigger probes manually
            executor_workers=0,
        )
        s.open()
        servers.append(s)
    yield servers
    for s in servers:
        try:
            s.close()
        except Exception:
            pass


def test_query_survives_node_death(cluster3r):
    client = InternalClient()
    h0 = f"localhost:{cluster3r[0].port}"
    client.create_index(h0, "fi")
    client.create_field(h0, "fi", "f")
    time.sleep(0.05)
    # Pick a shard node0 does NOT replicate (exists with overwhelming
    # probability within 64 shards; placement depends on ephemeral ports).
    s0 = cluster3r[0]
    target_shard = target_id = None
    for shard in range(64):
        owners = s0.cluster.shard_nodes("fi", shard)
        if all(n.id != s0.node.id for n in owners):
            target_shard, target_id = shard, owners[0].id
            break
    assert target_id is not None, "placement gave node0 every shard in 0..63"
    cols = [1, SHARD_WIDTH + 2, target_shard * SHARD_WIDTH + 3]
    for col in cols:
        client.query(h0, "fi", f"Set({col}, f=1)")
    cols = sorted(set(cols))
    assert client.query(h0, "fi", "Count(Row(f=1))")["results"][0] == len(cols)
    dead = next(s for s in cluster3r if s.node.id == target_id)
    dead.close()

    # Query from node0: remote call to the dead node fails, the executor
    # marks it unavailable and retries its shards on replicas.
    resp = client.query(h0, "fi", "Count(Row(f=1))")
    assert resp["results"][0] == len(cols)
    assert dead.node.id in s0.cluster.unavailable
    resp = client.query(h0, "fi", "Row(f=1)")
    assert resp["results"][0]["columns"] == cols


def test_member_monitor_detects_death_and_recovery(cluster3r):
    s0, s1, _ = cluster3r
    s0._monitor_members()
    assert s0.cluster.unavailable == set()
    port = s1.port
    s1.close()
    # Flap damping (gossip.probe-failures, default 3): one or two failed
    # probes are a blip, not a death — routing must not flap.
    s0._monitor_members()
    assert s1.node.id not in s0.cluster.unavailable
    s0._monitor_members()
    assert s1.node.id not in s0.cluster.unavailable
    s0._monitor_members()
    assert s1.node.id in s0.cluster.unavailable
    # Restart on the same port -> recovery detected.
    s1b = Server(
        data_dir=s1.data_dir,
        port=port,
        cluster_hosts=[n.uri for n in s0.cluster.nodes],
        replica_n=2,
        hasher=ModHasher(),
        cache_flush_interval=0,
        member_monitor_interval=0,
        executor_workers=0,
    )
    s1b.open()
    try:
        s0._monitor_members()
        assert s1b.node.id not in s0.cluster.unavailable
    finally:
        s1b.close()


def test_writes_survive_replica_death(cluster3r):
    """Write fan-out tolerates a dead peer the way the read path does:
    Set/SetRowAttrs/SetValue succeed when one replica of the target shard is
    down (anti-entropy repairs it later), instead of raising after a client
    timeout. The dead node gets marked unavailable by the failed forward."""
    client = InternalClient()
    s0 = cluster3r[0]
    h0 = f"localhost:{s0.port}"
    client.create_index(h0, "wr")
    client.create_field(h0, "wr", "f")
    client.create_field(h0, "wr", "v", {"type": "int", "min": 0, "max": 100})
    time.sleep(0.05)
    # Find a shard node0 owns whose OTHER replica is some other node.
    target_shard = dead_id = None
    for shard in range(64):
        owners = s0.cluster.shard_nodes("wr", shard)
        if any(n.id == s0.node.id for n in owners):
            others = [n.id for n in owners if n.id != s0.node.id]
            if others:
                target_shard, dead_id = shard, others[0]
                break
    assert dead_id is not None
    dead = next(s for s in cluster3r if s.node.id == dead_id)
    dead.close()

    col = target_shard * SHARD_WIDTH + 7
    # Bit write: local apply + dead-replica forward -> still succeeds.
    assert client.query(h0, "wr", f"Set({col}, f=2)")["results"][0] is True
    assert dead_id in s0.cluster.unavailable
    # Attr + BSI writes fan to ALL nodes; the dead one is now skipped fast.
    client.query(h0, "wr", 'SetRowAttrs(f, 2, tag="x")')
    client.query(h0, "wr", f"SetValue(col={col}, v=42)")
    assert s0.holder.field("wr", "f").row_attr_store.attrs(2) == {"tag": "x"}
    assert client.query(h0, "wr", "Count(Row(f=2))")["results"][0] == 1

    # The surviving replica set still answers for the written bit.
    live = [s for s in cluster3r if s.node.id != dead_id and s is not s0]
    for s in live:
        resp = client.query(f"localhost:{s.port}", "wr", "Count(Row(f=2))")
        assert resp["results"][0] == 1


def test_write_fails_when_all_owners_dead(cluster3r):
    """If every owner of the target shard is unreachable the write raises
    instead of silently dropping (no false ack)."""
    client = InternalClient()
    s0 = cluster3r[0]
    h0 = f"localhost:{s0.port}"
    client.create_index(h0, "wx")
    client.create_field(h0, "wx", "f")
    time.sleep(0.05)
    # Find a shard node0 does NOT own.
    target_shard = None
    for shard in range(64):
        owners = s0.cluster.shard_nodes("wx", shard)
        if all(n.id != s0.node.id for n in owners):
            target_shard = shard
            break
    assert target_shard is not None
    cluster3r[1].close()
    cluster3r[2].close()
    with pytest.raises(ClientError):
        client.query(h0, "wx", f"Set({target_shard * SHARD_WIDTH + 1}, f=1)")


def test_no_available_replica_errors(cluster3r):
    client = InternalClient()
    h0 = f"localhost:{cluster3r[0].port}"
    client.create_index(h0, "fx")
    client.create_field(h0, "fx", "f")
    time.sleep(0.05)
    client.query(h0, "fx", f"Set({SHARD_WIDTH + 1}, f=1)")
    # Kill both non-local nodes; shards owned only by them are unreachable.
    cluster3r[1].close()
    cluster3r[2].close()
    # Some shard will have no available owner -> error, not silent data loss.
    s0 = cluster3r[0]
    unreachable = [
        sh for sh in range(2)
        if all(n.id != s0.node.id for n in s0.cluster.shard_nodes("fx", sh))
    ]
    if unreachable:
        with pytest.raises(ClientError):
            client.query(h0, "fx", "Count(Row(f=1))")


def test_4xx_replica_error_not_misclassified_as_node_death():
    """ADVICE r3: a deterministic application error (4xx) from a replica
    must surface to the caller, not mark the healthy node unavailable."""
    from pilosa_tpu.cluster.node import Cluster, Node
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    nodes = [Node(id="n0"), Node(id="n1")]
    cluster = Cluster(node=nodes[0], nodes=nodes, replica_n=1, hasher=ModHasher())

    class FakeClient:
        def __init__(self, status):
            self.status = status
            self.calls = 0

        def query_node(self, node, index, query, shards=None, remote=True):
            self.calls += 1
            raise ClientError("boom", status=self.status)

    holder = Holder(None)
    holder.open()
    idx = holder.create_index("fz")
    idx.create_field("f")
    # Ensure some shard in 0..3 is owned by the remote node (ModHasher).
    remote_shard = next(
        s for s in range(4)
        if cluster.shard_nodes("fz", s)[0].id == "n1"
    )

    # 400: surfaces, node stays available.
    client = FakeClient(400)
    ex = Executor(holder, cluster=cluster, client=client, workers=0)
    with pytest.raises(ClientError):
        ex.execute("fz", "Count(Row(f=1))", shards=[remote_shard])
    assert "n1" not in cluster.unavailable
    assert client.calls == 1

    # Transport failure (status 0): marked unavailable, shards re-mapped
    # (single replica here, so the retry exhausts owners and errors).
    cluster.unavailable.clear()
    client = FakeClient(0)
    ex = Executor(holder, cluster=cluster, client=client, workers=0)
    with pytest.raises(PilosaError):
        ex.execute("fz", "Count(Row(f=1))", shards=[remote_shard])
    assert "n1" in cluster.unavailable


def test_legacy_topology_without_node_records_still_solicits():
    """ADVICE r3: topology files that predate full node records (nodeIDs
    only) must still let a restarting coordinator dial prior members —
    ids are URIs in static mode."""
    import json
    import tempfile

    from pilosa_tpu.cluster.topology import Topology

    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/.topology"
        with open(path, "w") as f:
            json.dump({"nodeIDs": ["localhost:1001", "localhost:1002"]}, f)
        t = Topology.load(path)
        assert [n.id for n in t.nodes] == ["localhost:1001", "localhost:1002"]
        assert [n.uri for n in t.nodes] == ["localhost:1001", "localhost:1002"]


def test_import_tolerates_dead_replica(cluster3r):
    """Bulk import succeeds when a replica is down (the dead node is
    marked unavailable and skipped, matching the executor's tolerant
    write fan-out); previously the first ClientError failed the whole
    import even though the primary had applied it."""
    import numpy as np

    client = InternalClient()
    h0 = f"localhost:{cluster3r[0].port}"
    client.create_index(h0, "imp")
    client.create_field(h0, "imp", "f")
    time.sleep(0.05)

    owners = cluster3r[0].cluster.shard_nodes("imp", 0)
    primary = next(s for s in cluster3r if s.node.id == owners[0].id)
    replica = next(s for s in cluster3r if s.node.id == owners[1].id)
    replica.close()  # replica dies

    rows = np.zeros(100, dtype=np.uint64)
    cols = np.arange(100, dtype=np.uint64)
    primary.api.import_bits("imp", "f", 0, rows.tolist(), cols.tolist())
    assert primary.holder.fragment("imp", "f", "standard", 0).row_count(0) == 100
    assert replica.node.id in primary.cluster.unavailable


def test_write_fanout_replica_flap_converges(cluster3r, tmp_path):
    """tolerant_owner_fanout under a replica that flaps mid-write-stream
    (alive -> dead -> alive): the surviving owner applies every acked
    write exactly once, missed forwards are HINTED (breaker open, zero
    connect attempts — cluster/hints.py), the hint log drains to the
    returned replica, and anti-entropy finds byte-identical fragment
    state with nothing left to push."""
    import io

    from pilosa_tpu.cluster.health import CLOSED
    from pilosa_tpu.cluster.syncer import HolderSyncer

    client = InternalClient()
    s0 = cluster3r[0]
    h0 = f"localhost:{s0.port}"
    client.create_index(h0, "flap")
    client.create_field(h0, "flap", "f")
    time.sleep(0.05)

    # A shard s0 owns whose OTHER replica is some other node.
    target_shard = flap_id = None
    for shard in range(64):
        owners = s0.cluster.shard_nodes("flap", shard)
        if any(n.id == s0.node.id for n in owners):
            others = [n.id for n in owners if n.id != s0.node.id]
            if others:
                target_shard, flap_id = shard, others[0]
                break
    assert flap_id is not None
    flapper = next(s for s in cluster3r if s.node.id == flap_id)
    base = target_shard * SHARD_WIDTH

    def counter(name):
        return s0.stats.snapshot()["counters"].get(name, 0)

    # Phase 1: both owners alive.
    assert client.query(h0, "flap", f"Set({base + 1}, f=9)")["results"][0]

    # Phase 2: replica dies mid-stream. The first write pays the failed
    # forward and lands in the peer's hint log; later writes queue behind
    # it (per-peer FIFO) without a connect attempt.
    flap_port, flap_dir = flapper.port, flapper.data_dir
    flapper.close()
    assert client.query(h0, "flap", f"Set({base + 2}, f=9)")["results"][0]
    assert counter("WriteForwardFailed") >= 1
    assert counter("WriteForwardHinted") >= 1
    assert flap_id in s0.cluster.unavailable
    hinted_before = counter("WriteForwardHinted")
    assert client.query(h0, "flap", f"Set({base + 3}, f=9)")["results"][0]
    assert counter("WriteForwardHinted") > hinted_before
    assert s0.hints.pending(flap_id) >= 2

    # Phase 3: replica returns (same id, same data dir). The monitor's
    # successful probe recloses the breaker; the delivery daemon drains
    # the hint log; writes forward directly again.
    flapper2 = Server(
        data_dir=flap_dir,
        port=flap_port,
        cluster_hosts=[n.uri for n in s0.cluster.nodes],
        replica_n=2,
        hasher=ModHasher(),
        cache_flush_interval=0,
        anti_entropy_interval=0,
        member_monitor_interval=0,
        executor_workers=0,
    )
    flapper2.open()
    try:
        s0._monitor_members()
        assert flap_id not in s0.cluster.unavailable
        assert s0.cluster.health.state(flap_id) == CLOSED
        # The delivery daemon (deliver-interval default 1s) replays the
        # missed Sets in order; poll until the backlog clears.
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and s0.hints.pending(flap_id):
            time.sleep(0.05)
        assert s0.hints.pending(flap_id) == 0
        assert client.query(h0, "flap", f"Set({base + 4}, f=9)")["results"][0]

        # No double-apply on the surviving owner: exactly the 4 distinct
        # bits, each applied once (a replayed Set would return False and
        # not change the count, a double-applied forward would diverge
        # replicas — both show up as a count mismatch somewhere below).
        frag0 = s0.holder.fragment("flap", "f", "standard", target_shard)
        assert frag0.row_count(9) == 4
        # The flapped replica got bits 2 and 3 from the hint drain and
        # bit 4 as a direct forward — no anti-entropy sweep needed.
        fragX = flapper2.holder.fragment("flap", "f", "standard", target_shard)
        assert fragX is not None and fragX.row_count(9) == 4

        # Phase 4: anti-entropy finds nothing left to repair; state is
        # byte-identical with the survivor.
        HolderSyncer(s0).sync_holder()
        time.sleep(0.05)
        fragX = flapper2.holder.fragment("flap", "f", "standard", target_shard)
        assert fragX.row_count(9) == 4
        b0, bX = io.BytesIO(), io.BytesIO()
        frag0.write_to(b0)
        fragX.write_to(bX)
        assert b0.getvalue() == bX.getvalue()
    finally:
        flapper2.close()


def test_write_forward_counters_survive_statsless_holder():
    """Regression (pilint R10, the PR 12 crash class): the write-forward
    fan-out's breaker counters ride the _count_stat guard, so a
    stats-less holder (Holder(None), library embedders) skips the count
    instead of crashing the degraded path — pre-fix,
    self.holder.stats.count raised AttributeError the moment a peer
    failed or its breaker opened."""
    from pilosa_tpu.cluster.node import Cluster, Node
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import ExecOptions, Executor
    from pilosa_tpu.pql.parser import parse

    nodes = [Node(id="n0"), Node(id="n1")]
    cluster = Cluster(node=nodes[0], nodes=nodes, replica_n=1,
                      hasher=ModHasher())

    class FakeClient:
        def __init__(self):
            self.calls = 0

        def query_node(self, node, index, query, shards=None, remote=True):
            self.calls += 1
            raise ClientError("boom", status=0)  # transport failure

    holder = Holder(None)
    holder.open()
    assert holder.stats is None
    client = FakeClient()
    ex = Executor(holder, cluster=cluster, client=client, workers=0)
    call = parse('SetRowAttrs(f, 1, x="y")').calls[0]

    # Failed-forward path: WriteForwardFailed rides the guard.
    ex._forward_to_all("fz", call, ExecOptions())
    assert client.calls == 1
    # Breaker now open: the skip path counts WriteForwardSkipped through
    # the guard and issues zero connect attempts.
    ex._forward_to_all("fz", call, ExecOptions())
    assert client.calls == 1

    # The single-target tolerant step takes the same guard on both arms.
    errors = []
    res = ex._forward_tolerant(nodes[1], lambda n: True, errors,
                               lambda e: None)
    assert res is None
    assert errors and "breaker open" in errors[0]
