"""Network-chaos harness: a 3-node cluster under injected link faults.

Faults ride the `client-send` failpoint with per-peer targeting
(failpoints.py network actions: drop / latency(ms) / flaky(p)), so one
node's links misbehave while the harness's own connection to the query
head stays clean. The invariant under ANY fault schedule:

    every query either returns the correct result or fails with a typed
    error (ClientError / PilosaError) — never wrong data;

and once faults clear, routing converges: every breaker re-closes, no
peer stays marked unavailable, and queries succeed with zero degraded
reads.

Two tiers:
  - test_chaos_smoke: deterministic (pinned seed, fake breaker clock,
    ~10s), runs in tier-1.
  - test_chaos_randomized: the full randomized sweep, marked `slow`;
    CHAOS_SMOKE=1 shrinks it to the fast deterministic mode so the whole
    path can be exercised quickly (seed printed for replay via
    PILOSA_TPU_CHAOS_SEED).
"""

import os
import random
import socket
import time

import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.cluster.health import CLOSED, ResilienceConfig
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.errors import PilosaError
from pilosa_tpu.server.client import ClientError, InternalClient
from pilosa_tpu.server.server import Server

from .conftest import FakeClock

pytestmark = pytest.mark.chaos

N_SHARDS = 4
ROWS = (1, 2, 3)


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def chaos_cluster(tmp_path):
    """3-node replica_n=2 cluster with tight breaker backoffs, manual
    member-monitor rounds, and a shared fake clock driving every node's
    breaker timing."""
    clock = FakeClock()
    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        s = Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=port,
            cluster_hosts=hosts,
            replica_n=2,
            hasher=ModHasher(),
            cache_flush_interval=0,
            anti_entropy_interval=0,
            member_monitor_interval=0,  # rounds driven by the test
            executor_workers=0,
            resilience_config=ResilienceConfig(
                breaker_backoff=0.2, breaker_backoff_max=1.0,
                # Generous budget: the invariant under test is
                # correctness, not shedding (test_health covers that).
                retry_budget=50.0, retry_refill=1.0,
            ),
        )
        s.open()
        s.cluster.health.clock = clock
        servers.append(s)
    yield servers, hosts, clock
    failpoints.reset()
    for s in servers:
        try:
            s.close()
        except Exception:
            pass


def _rq(client, h0, q, deadline_s=20.0):
    """Query with transport-flake tolerance for the NO-FAULT phases
    (load, convergence): under full-suite box load a 10s socket timeout
    can trip with zero injected faults, which used to fail the smoke
    outright (known flake since PR 10). A transport-shaped error
    (status 0 — timeout, connect failure) retries within a bounded
    deadline; an application error (4xx/5xx) or wrong data still
    surfaces immediately, so the correctness contract is untouched."""
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return client.query(h0, "cx", q)
        except ClientError as e:
            if getattr(e, "status", 0) != 0 or time.monotonic() >= deadline:
                raise
            time.sleep(0.1)


def _load(client, h0):
    """Deterministic dataset spanning every shard; returns expected
    Count(Row(f=r)) per row. Idempotent: the randomized sweep replays it
    on the same cluster once per seed."""
    client.ensure_index(h0, "cx")
    client.ensure_field(h0, "cx", "f")
    time.sleep(0.05)
    expected = {}
    for row in ROWS:
        cols = [s * SHARD_WIDTH + 17 * row + k for s in range(N_SHARDS)
                for k in range(row)]
        for col in cols:
            _rq(client, h0, f"Set({col}, f={row})")
        expected[row] = len(set(cols))
    # Sanity before faults.
    for row, want in expected.items():
        assert _rq(client, h0, f"Count(Row(f={row}))")["results"][0] == want
    return expected


def _run_chaos(servers, hosts, clock, seed, rounds, queries_per_round):
    """Drive seed-pinned randomized faults; assert correct-or-clean-error
    per query; return (ok_count, err_count)."""
    rng = random.Random(seed)
    failpoints.seed(seed)
    client = InternalClient(timeout=10.0)
    h0 = hosts[0]
    expected = _load(client, h0)
    peers = hosts[1:]  # never fault the harness -> query-head link

    ok = err = 0
    for _ in range(rounds):
        failpoints.reset()
        failpoints.seed(rng.randrange(1 << 30))
        # 1-2 faulted peer links per round, random action each.
        for netloc in rng.sample(peers, rng.randint(1, 2)):
            action = rng.choice(["drop", "flaky", "latency"])
            arg = {"drop": 0.0, "flaky": 0.6, "latency": 3.0}[action]
            failpoints.configure(f"client-send@{netloc}", action, arg=arg)
        for _ in range(queries_per_round):
            row = rng.choice(ROWS)
            try:
                got = client.query(h0, "cx", f"Count(Row(f={row}))")
            except (ClientError, PilosaError):
                err += 1  # clean failure: acceptable under faults
                continue
            assert got["results"][0] == expected[row], (
                f"WRONG RESULT under faults (seed={seed}): row {row} "
                f"got {got['results'][0]} want {expected[row]}"
            )
            ok += 1
        # Let breaker backoffs elapse between rounds so re-admission
        # probes interleave with new faults.
        clock.advance(rng.choice([0.0, 0.25, 1.1]))

    # ---- faults clear: routing must converge.
    failpoints.reset()
    clock.advance(2.0)  # every backoff elapsed
    for _ in range(3):
        for s in servers:
            s._monitor_members()
    for s in servers:
        snap = s.cluster.health.snapshot()
        for pid, p in snap["peers"].items():
            assert p["state"] == CLOSED, (
                f"breaker for {pid} on {s.node.id} stuck {p['state']} "
                f"(seed={seed}): {snap}"
            )
        assert s.cluster.unavailable == set()
    for row, want in expected.items():
        got = _rq(client, h0, f"Count(Row(f={row}))")
        assert got["results"][0] == want
    # Zero degraded reads after recovery: nothing quarantined, nothing
    # served from an empty fragment.
    for s in servers:
        assert s.executor.quarantined_reads == 0
        assert s.holder.quarantined_fragments() == []
    assert ok > 0, "chaos run never completed a single successful query"
    return ok, err


def test_chaos_smoke(chaos_cluster):
    """Deterministic tier-1 smoke: pinned seed, fake breaker clock, small
    schedule (~10s). Under drop/latency/flaky faults on two of three
    nodes' links, no query ever returns a wrong count, and routing
    converges once the faults clear."""
    servers, hosts, clock = chaos_cluster
    seed = int(os.environ.get("PILOSA_TPU_CHAOS_SEED", "1207"))
    _run_chaos(servers, hosts, clock, seed, rounds=6, queries_per_round=5)


@pytest.mark.slow
def test_chaos_randomized(chaos_cluster):
    """Full randomized sweep (slow): fresh seed per run, printed for
    replay. CHAOS_SMOKE=1 shrinks it to one fast deterministic pass."""
    servers, hosts, clock = chaos_cluster
    if os.environ.get("CHAOS_SMOKE") == "1":
        seeds, rounds, qpr = [1207], 6, 5
    else:
        base = int(os.environ.get("PILOSA_TPU_CHAOS_SEED",
                                  str(random.randrange(1 << 30))))
        print(f"chaos: base seed {base} (replay with "
              f"PILOSA_TPU_CHAOS_SEED={base})")
        seeds, rounds, qpr = [base + i for i in range(3)], 12, 10
    for seed in seeds:
        _run_chaos(servers, hosts, clock, seed, rounds, qpr)


def test_network_failpoint_grammar():
    """The network fault spec grammar parses and reports correctly."""
    try:
        failpoints.activate(
            "client-send@localhost:1=drop;"
            "client-send@localhost:2=latency(5);"
            "client-send@localhost:3=3*flaky(0.5)"
        )
        active = failpoints.active()
        assert active["client-send@localhost:1"] == "drop"
        assert active["client-send@localhost:2"] == "latency(5)"
        assert active["client-send@localhost:3"] == "3*flaky(0.5)"
        with pytest.raises(ValueError):
            failpoints.activate("client-send=flaky(nope)")
        with pytest.raises(ValueError):
            failpoints.configure("x", "flaky", arg=1.5)  # pilint: allow-failpoint(grammar test: validates rejection, never fires)
    finally:
        failpoints.reset()


def test_targeted_failpoint_scopes_to_peer():
    """A targeted spec fires only for its peer; a bare spec matches all;
    the targeted entry wins when both exist."""
    try:
        failpoints.configure("client-send@peer-a:1", "drop")
        failpoints.fire("client-send", target="peer-b:1")  # no match: clean
        with pytest.raises(failpoints.InjectedFault):
            failpoints.fire("client-send", target="peer-a:1")
        assert failpoints.hits("client-send@peer-a:1") == 1
        failpoints.configure("client-send", "latency", arg=0.0)
        failpoints.fire("client-send", target="peer-b:1")  # bare latency
        assert failpoints.hits("client-send") == 1
        with pytest.raises(failpoints.InjectedFault):
            failpoints.fire("client-send", target="peer-a:1")  # targeted wins
    finally:
        failpoints.reset()


def test_flaky_failpoint_is_seed_deterministic():
    """flaky(p) draws replay bit-identically under the same seed."""
    def draws(seed):
        failpoints.reset()
        failpoints.seed(seed)
        failpoints.configure("p", "flaky", arg=0.5)  # pilint: allow-failpoint(registry test fires the point by hand below)
        out = []
        for _ in range(32):
            try:
                failpoints.fire("p")
                out.append(0)
            except failpoints.InjectedFault:
                out.append(1)
        failpoints.reset()
        return out

    a, b = draws(99), draws(99)
    assert a == b
    assert 0 < sum(a) < 32  # actually flaky, not constant
