"""Generalized multi-host collective plane (parallel/collective.py).

Unit level: placement follows the REAL jump-hash cluster placement,
ownership is verified at entry (the round-3 silent-zeros bug), the runner
executes descriptors in cluster-wide seq order.

Integration level (the flagship): TWO real Server processes joined in one
jax.distributed job, data imported through the normal cluster write path
(jump-hash placement), and Count / TopN / Sum answered through the
collective backend — plus the failure mode: a peer that drops descriptors
makes the leader's barrier time out and the query falls back to the HTTP
fan-out instead of hanging (VERDICT r3 items 2-4).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap
import time
from concurrent.futures import Future

import numpy as np
import pytest

from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.cluster.node import Cluster, Node
from pilosa_tpu.parallel.collective import (
    CollectiveUnavailable,
    _Runner,
    placement,
)


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ----------------------------------------------------------------- placement


def test_placement_follows_jump_hash():
    nodes = [
        Node(id="n0", process_idx=0),
        Node(id="n1", process_idx=1),
        Node(id="n2", process_idx=2),
    ]
    c = Cluster(node=nodes[0], nodes=nodes, replica_n=1)
    n_shards = 64
    slots = placement(c, "i", n_shards, 3)
    assert sorted(s for lst in slots for s in lst) == list(range(n_shards))
    for p, lst in enumerate(slots):
        for s in lst:
            owners = c.shard_nodes("i", s)
            assert owners[0].process_idx == p, (s, p, owners[0].id)


def test_placement_prefers_available_replica():
    nodes = [
        Node(id="n0", process_idx=0),
        Node(id="n1", process_idx=1),
    ]
    c = Cluster(node=nodes[0], nodes=nodes, replica_n=2, hasher=ModHasher())
    c.mark_unavailable("n0")
    slots = placement(c, "i", 8, 2)
    assert slots[0] == []  # nothing assigned to the dead node's process
    assert sorted(slots[1]) == list(range(8))


def test_placement_requires_process_idx():
    nodes = [Node(id="n0", process_idx=0), Node(id="n1")]  # n1 unknown
    c = Cluster(node=nodes[0], nodes=nodes, replica_n=1, hasher=ModHasher())
    with pytest.raises(CollectiveUnavailable, match="process index"):
        placement(c, "i", 8, 2)


def test_ownership_verification_refuses_unowned_shard():
    """The round-3 bug: a process silently contributed zeros for shards it
    did not own. Entry must refuse instead."""
    from types import SimpleNamespace

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.logger import NopLogger
    from pilosa_tpu.parallel.collective import CollectiveBackend

    nodes = [Node(id="n0", process_idx=0), Node(id="n1", process_idx=1)]
    cluster = Cluster(node=nodes[0], nodes=nodes, replica_n=1, hasher=ModHasher())
    holder = Holder(None)
    holder.open()
    backend = CollectiveBackend(SimpleNamespace(
        holder=holder, logger=NopLogger(), cluster=cluster, client=None,
    ))
    try:
        # ModHasher, 2 nodes: n0 owns even partitions' shards only.
        owned = [s for s in range(8) if cluster.owns_shard("n0", "i", s)]
        unowned = [s for s in range(8) if not cluster.owns_shard("n0", "i", s)]
        assert owned and unowned
        backend._verify_ownership("i", owned)  # fine
        with pytest.raises(CollectiveUnavailable, match="placement mismatch"):
            backend._verify_ownership("i", [unowned[0]])
    finally:
        backend.close()


# -------------------------------------------------------------------- runner


class _StubBackend:
    def __init__(self):
        self.order = []

    def _enter(self, desc):
        self.order.append(desc["seq"])
        return desc["seq"] * 10


def test_runner_executes_in_seq_order():
    b = _StubBackend()
    r = _Runner(b)
    try:
        # Submit out of order; runner must execute 1, 2, 3.
        futs = {}
        futs[2] = r.submit({"seq": 2})
        futs[3] = r.submit({"seq": 3})
        futs[1] = r.submit({"seq": 1})
        for seq, fut in futs.items():
            assert fut.result(timeout=10) == seq * 10
        assert b.order == sorted(b.order)
    finally:
        r.close()


def test_runner_advances_past_seq_gap():
    """A leader that died between seq allocation and broadcast must not
    stall the queue forever — bounded gap wait, then proceed."""
    b = _StubBackend()
    r = _Runner(b)
    r.GAP_TIMEOUT = 0.2
    try:
        fut = r.submit({"seq": 5})  # seqs 1-4 never arrive
        assert fut.result(timeout=10) == 50
    finally:
        r.close()


# ------------------------------------------- two-process cluster integration

WORKER = textwrap.dedent("""
    import json, os, re, sys, time
    import urllib.request

    # Replace (not append) any inherited device-count flag: pytest's
    # conftest exports an 8-device one, and duplicate flags are ambiguous.
    flags = re.sub(r"--xla_force_host_platform_device_count=\\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    jax_coord, pid, port0, port1, tmp = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
        sys.argv[5],
    )
    os.environ["PILOSA_JAX_COORDINATOR"] = jax_coord
    os.environ["PILOSA_JAX_NUM_PROCESSES"] = "2"
    os.environ["PILOSA_JAX_PROCESS_ID"] = str(pid)
    os.environ["PILOSA_COLLECTIVE_TIMEOUT_MS"] = "4000"

    from pilosa_tpu.server.client import InternalClient
    from pilosa_tpu.server.server import Server

    # Trace collective entries to stderr: on failure pytest shows exactly
    # which seq/kind each process entered and whether it completed.
    from pilosa_tpu.parallel import collective as coll

    _orig_enter = coll.CollectiveBackend._enter

    def _traced_enter(self, desc):
        print(f"[p{pid}] enter seq={desc['seq']} kind={desc['kind']} "
              f"slots={desc['slots']}", file=sys.stderr, flush=True)
        try:
            r = _orig_enter(self, desc)
            print(f"[p{pid}] done seq={desc['seq']} -> {r}",
                  file=sys.stderr, flush=True)
            return r
        except BaseException as e:
            print(f"[p{pid}] FAILED seq={desc['seq']}: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
            raise

    coll.CollectiveBackend._enter = _traced_enter

    SW = 1 << 20
    hosts = [f"localhost:{port0}", f"localhost:{port1}"]
    s = Server(
        data_dir=f"{tmp}/node{pid}",
        port=[port0, port1][pid],
        cluster_hosts=hosts,
        replica_n=1,
        cache_flush_interval=0,
        anti_entropy_interval=0,
        member_monitor_interval=0.2,
        executor_workers=0,
    )
    s.open()
    try:
        if pid == 1:
            # Serve until the driver finishes; honor the drop-collective
            # order (failure-mode phase) when the sentinel appears.
            dropped = False
            while not os.path.exists(f"{tmp}/done"):
                if not dropped and os.path.exists(f"{tmp}/drop"):
                    s.collective.receive = lambda desc: None
                    dropped = True
                time.sleep(0.05)
            print("WORKER1_OK")
            sys.exit(0)

        client = InternalClient()
        h = hosts[0]

        # Wait for both processes' indexes to propagate (status probes).
        deadline = time.time() + 30
        while time.time() < deadline and not s.collective.active():
            time.sleep(0.1)
        assert s.collective.active(), [
            (n.id, n.process_idx) for n in s.cluster.nodes
        ]

        client.create_index(h, "ci")
        client.create_field(h, "ci", "f")
        client.create_field(h, "ci", "v",
                            {"type": "int", "min": 0, "max": 255})

        # Data through the NORMAL cluster write path: jump-hash placement
        # decides which node stores each shard's fragment.
        row1 = [5, SW + 1, 3 * SW + 7, 11]
        row2 = [5, SW + 1, 9]
        for col in row1:
            client.query(h, "ci", f"Set({col}, f=1)")
        for col in row2:
            client.query(h, "ci", f"Set({col}, f=2)")
        vals = {5: 10, 9: 20, SW + 1: 30}
        for col, val in vals.items():
            client.query(h, "ci", f"SetValue(col={col}, v={val})")

        def counter(name):
            raw = urllib.request.urlopen(
                f"http://{h}/debug/vars", timeout=5
            ).read()
            return json.loads(raw)["counters"].get(name, 0)

        # --- Count through the collective plane.
        got = client.query(h, "ci", "Count(Intersect(Row(f=1), Row(f=2)))")
        assert got["results"][0] == 2, got
        assert counter("CollectiveCount") >= 1, "collective path not taken"

        # --- TopN: phase-2 candidate counts through the collective plane.
        got = client.query(h, "ci", "TopN(f, n=5)")
        pairs = {p["id"]: p["count"] for p in got["results"][0]}
        assert pairs == {1: 4, 2: 3}, pairs
        assert counter("CollectiveTopN") >= 1

        # --- Sum / Min / Max through the collective plane.
        got = client.query(h, "ci", "Sum(field=v)")
        assert got["results"][0] == {"value": 60, "count": 3}, got
        got = client.query(h, "ci", "Sum(Row(f=1), field=v)")
        assert got["results"][0] == {"value": 40, "count": 2}, got
        got = client.query(h, "ci", "Min(field=v)")
        assert got["results"][0] == {"value": 10, "count": 1}, got
        got = client.query(h, "ci", "Max(field=v)")
        assert got["results"][0] == {"value": 30, "count": 1}, got
        assert counter("CollectiveValCount") >= 4

        # --- Failure mode: the peer starts dropping descriptors. The
        # leader's barrier must time out and the query fall back to the
        # HTTP fan-out — same answer, no hang (VERDICT r3 item 4).
        open(f"{tmp}/drop", "w").close()
        time.sleep(0.3)
        t0 = time.time()
        got = client.query(h, "ci", "Count(Intersect(Row(f=1), Row(f=2)))")
        elapsed = time.time() - t0
        assert got["results"][0] == 2, got
        assert counter("CollectiveFallback") >= 1, "no fallback recorded"
        assert elapsed < 25, f"leader stalled {elapsed}s"
        print(f"WORKER0_OK fallback_after={elapsed:.1f}s")
    finally:
        open(f"{tmp}/done", "w").close()
        s.close()
""")


@pytest.mark.parametrize("n_proc", [2])
def test_two_process_cluster_collective_queries(tmp_path, n_proc):
    jax_port = free_port()
    http_ports = [free_port(), free_port()]
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), f"localhost:{jax_port}", str(pid),
             str(http_ports[0]), str(http_ports[1]), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for pid in range(n_proc)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-3000:]}"
    assert any("WORKER0_OK" in out for _, out, _ in outs)
    assert any("WORKER1_OK" in out for _, out, _ in outs)


def test_runner_rejects_stale_seq():
    """A gap-skipped descriptor arriving late must be rejected, not
    executed — its barrier peers already timed out."""
    b = _StubBackend()
    r = _Runner(b)
    r.GAP_TIMEOUT = 0.2
    try:
        assert r.submit({"seq": 5}).result(timeout=10) == 50
        fut = r.submit({"seq": 3})  # late arrival from a slow broadcast
        with pytest.raises(CollectiveUnavailable, match="stale"):
            fut.result(timeout=10)
        assert b.order == [5]
    finally:
        r.close()
