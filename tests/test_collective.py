"""Generalized multi-host collective plane (parallel/collective.py).

Unit level: placement follows the REAL jump-hash cluster placement,
ownership is verified at entry (the round-3 silent-zeros bug), the runner
executes descriptors in cluster-wide seq order.

Integration level (the flagship): TWO real Server processes joined in one
jax.distributed job, data imported through the normal cluster write path
(jump-hash placement), and Count / TopN / Sum answered through the
collective backend — plus the failure mode: a peer that drops descriptors
makes the leader's barrier time out and the query falls back to the HTTP
fan-out instead of hanging (VERDICT r3 items 2-4).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap
import time
from concurrent.futures import Future

import numpy as np
import pytest

from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.cluster.node import Cluster, Node
from pilosa_tpu.parallel.collective import (
    CollectiveUnavailable,
    _Runner,
    placement,
)


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ----------------------------------------------------------------- placement


def test_placement_follows_jump_hash():
    nodes = [
        Node(id="n0", process_idx=0),
        Node(id="n1", process_idx=1),
        Node(id="n2", process_idx=2),
    ]
    c = Cluster(node=nodes[0], nodes=nodes, replica_n=1)
    n_shards = 64
    slots = placement(c, "i", n_shards, 3)
    assert sorted(s for lst in slots for s in lst) == list(range(n_shards))
    for p, lst in enumerate(slots):
        for s in lst:
            owners = c.shard_nodes("i", s)
            assert owners[0].process_idx == p, (s, p, owners[0].id)


def test_placement_prefers_available_replica():
    nodes = [
        Node(id="n0", process_idx=0),
        Node(id="n1", process_idx=1),
    ]
    c = Cluster(node=nodes[0], nodes=nodes, replica_n=2, hasher=ModHasher())
    c.mark_unavailable("n0")
    slots = placement(c, "i", 8, 2)
    assert slots[0] == []  # nothing assigned to the dead node's process
    assert sorted(slots[1]) == list(range(8))


def test_placement_requires_process_idx():
    nodes = [Node(id="n0", process_idx=0), Node(id="n1")]  # n1 unknown
    c = Cluster(node=nodes[0], nodes=nodes, replica_n=1, hasher=ModHasher())
    with pytest.raises(CollectiveUnavailable, match="process index"):
        placement(c, "i", 8, 2)


def test_ownership_verification_refuses_unowned_shard():
    """The round-3 bug: a process silently contributed zeros for shards it
    did not own. Entry must refuse instead."""
    from types import SimpleNamespace

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.logger import NopLogger
    from pilosa_tpu.parallel.collective import CollectiveBackend

    nodes = [Node(id="n0", process_idx=0), Node(id="n1", process_idx=1)]
    cluster = Cluster(node=nodes[0], nodes=nodes, replica_n=1, hasher=ModHasher())
    holder = Holder(None)
    holder.open()
    backend = CollectiveBackend(SimpleNamespace(
        holder=holder, logger=NopLogger(), cluster=cluster, client=None,
    ))
    try:
        # ModHasher, 2 nodes: n0 owns even partitions' shards only.
        owned = [s for s in range(8) if cluster.owns_shard("n0", "i", s)]
        unowned = [s for s in range(8) if not cluster.owns_shard("n0", "i", s)]
        assert owned and unowned
        backend._verify_ownership("i", owned)  # fine
        with pytest.raises(CollectiveUnavailable, match="placement mismatch"):
            backend._verify_ownership("i", [unowned[0]])
    finally:
        backend.close()


# -------------------------------------------------------------------- runner


class _StubBackend:
    def __init__(self):
        self.order = []

    def _enter(self, desc):
        self.order.append(desc["seq"])
        return desc["seq"] * 10


def test_runner_executes_in_seq_order():
    b = _StubBackend()
    r = _Runner(b)
    try:
        # Submit out of order; runner must execute 1, 2, 3.
        futs = {}
        futs[2] = r.submit({"seq": 2})
        futs[3] = r.submit({"seq": 3})
        futs[1] = r.submit({"seq": 1})
        for seq, fut in futs.items():
            assert fut.result(timeout=10) == seq * 10
        assert b.order == sorted(b.order)
    finally:
        r.close()


def test_runner_advances_past_seq_gap():
    """A leader that died between seq allocation and broadcast must not
    stall the queue forever — bounded gap wait, then proceed."""
    b = _StubBackend()
    r = _Runner(b)
    r.GAP_TIMEOUT = 0.2
    try:
        fut = r.submit({"seq": 5})  # seqs 1-4 never arrive
        assert fut.result(timeout=10) == 50
    finally:
        r.close()


# ------------------------------------------- two-process cluster integration

WORKER = textwrap.dedent("""
    import json, os, re, sys, time
    import urllib.request

    # Replace (not append) any inherited device-count flag: pytest's
    # conftest exports an 8-device one, and duplicate flags are ambiguous.
    flags = re.sub(r"--xla_force_host_platform_device_count=\\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    jax_coord, pid, port0, port1, tmp = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
        sys.argv[5],
    )
    os.environ["PILOSA_JAX_COORDINATOR"] = jax_coord
    os.environ["PILOSA_JAX_NUM_PROCESSES"] = "2"
    os.environ["PILOSA_JAX_PROCESS_ID"] = str(pid)
    os.environ["PILOSA_COLLECTIVE_TIMEOUT_MS"] = "4000"

    from pilosa_tpu.server.client import InternalClient
    from pilosa_tpu.server.server import Server

    # Trace collective entries to stderr: on failure pytest shows exactly
    # which seq/kind each process entered and whether it completed.
    from pilosa_tpu.parallel import collective as coll

    _orig_enter = coll.CollectiveBackend._enter

    def _traced_enter(self, desc):
        print(f"[p{pid}] enter seq={desc['seq']} kind={desc['kind']} "
              f"slots={desc['slots']}", file=sys.stderr, flush=True)
        try:
            r = _orig_enter(self, desc)
            print(f"[p{pid}] done seq={desc['seq']} -> {r}",
                  file=sys.stderr, flush=True)
            return r
        except BaseException as e:
            print(f"[p{pid}] FAILED seq={desc['seq']}: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
            raise

    coll.CollectiveBackend._enter = _traced_enter

    SW = 1 << 20
    hosts = [f"localhost:{port0}", f"localhost:{port1}"]
    s = Server(
        data_dir=f"{tmp}/node{pid}",
        port=[port0, port1][pid],
        cluster_hosts=hosts,
        replica_n=1,
        cache_flush_interval=0,
        anti_entropy_interval=0,
        member_monitor_interval=0.2,
        executor_workers=0,
    )
    s.open()
    try:
        if pid == 1:
            # Serve until the driver finishes; honor the drop-collective
            # order (failure-mode phase) when the sentinel appears.
            dropped = False
            while not os.path.exists(f"{tmp}/done"):
                if not dropped and os.path.exists(f"{tmp}/drop"):
                    s.collective.receive = lambda desc: None
                    dropped = True
                time.sleep(0.05)
            print("WORKER1_OK")
            sys.exit(0)

        client = InternalClient()
        h = hosts[0]

        # Wait for both processes' indexes to propagate (status probes).
        deadline = time.time() + 30
        while time.time() < deadline and not s.collective.active():
            time.sleep(0.1)
        assert s.collective.active(), [
            (n.id, n.process_idx) for n in s.cluster.nodes
        ]

        client.create_index(h, "ci")
        client.create_field(h, "ci", "f")
        client.create_field(h, "ci", "v",
                            {"type": "int", "min": 0, "max": 255})

        # Data through the NORMAL cluster write path: jump-hash placement
        # decides which node stores each shard's fragment.
        row1 = [5, SW + 1, 3 * SW + 7, 11]
        row2 = [5, SW + 1, 9]
        for col in row1:
            client.query(h, "ci", f"Set({col}, f=1)")
        for col in row2:
            client.query(h, "ci", f"Set({col}, f=2)")
        vals = {5: 10, 9: 20, SW + 1: 30}
        for col, val in vals.items():
            client.query(h, "ci", f"SetValue(col={col}, v={val})")

        def counter(name):
            raw = urllib.request.urlopen(
                f"http://{h}/debug/vars", timeout=5
            ).read()
            return json.loads(raw)["counters"].get(name, 0)

        # --- Count through the collective plane.
        got = client.query(h, "ci", "Count(Intersect(Row(f=1), Row(f=2)))")
        assert got["results"][0] == 2, got
        assert counter("CollectiveCount") >= 1, "collective path not taken"

        # --- TopN: phase-2 candidate counts through the collective plane.
        got = client.query(h, "ci", "TopN(f, n=5)")
        pairs = {p["id"]: p["count"] for p in got["results"][0]}
        assert pairs == {1: 4, 2: 3}, pairs
        assert counter("CollectiveTopN") >= 1

        # --- Sum / Min / Max through the collective plane.
        got = client.query(h, "ci", "Sum(field=v)")
        assert got["results"][0] == {"value": 60, "count": 3}, got
        got = client.query(h, "ci", "Sum(Row(f=1), field=v)")
        assert got["results"][0] == {"value": 40, "count": 2}, got
        got = client.query(h, "ci", "Min(field=v)")
        assert got["results"][0] == {"value": 10, "count": 1}, got
        got = client.query(h, "ci", "Max(field=v)")
        assert got["results"][0] == {"value": 30, "count": 1}, got
        assert counter("CollectiveValCount") >= 4

        # --- Failure mode: the peer starts dropping descriptors. The
        # leader's barrier must time out and the query fall back to the
        # HTTP fan-out — same answer, no hang (VERDICT r3 item 4).
        open(f"{tmp}/drop", "w").close()
        time.sleep(0.3)
        t0 = time.time()
        got = client.query(h, "ci", "Count(Intersect(Row(f=1), Row(f=2)))")
        elapsed = time.time() - t0
        assert got["results"][0] == 2, got
        assert counter("CollectiveFallback") >= 1, "no fallback recorded"
        assert elapsed < 25, f"leader stalled {elapsed}s"
        print(f"WORKER0_OK fallback_after={elapsed:.1f}s")
    finally:
        open(f"{tmp}/done", "w").close()
        s.close()
""")


@pytest.mark.parametrize("n_proc", [2])
def test_two_process_cluster_collective_queries(tmp_path, n_proc):
    jax_port = free_port()
    http_ports = [free_port(), free_port()]
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), f"localhost:{jax_port}", str(pid),
             str(http_ports[0]), str(http_ports[1]), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for pid in range(n_proc)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-3000:]}"
    assert any("WORKER0_OK" in out for _, out, _ in outs)
    assert any("WORKER1_OK" in out for _, out, _ in outs)


# --------------------------- resident stacks / batching / health (PR 12)


def _pod(holder, **cfg_kw):
    """Single-process, single-node backend over `holder` — the one-pod
    serving mode ([collective] single-process) every PR 12 unit test
    drives; the barrier degenerates to a no-op and the mesh is the
    8-device test mesh."""
    from types import SimpleNamespace

    from pilosa_tpu.logger import NopLogger
    from pilosa_tpu.parallel import CollectiveConfig
    from pilosa_tpu.parallel.collective import CollectiveBackend

    node = Node(id="n0", process_idx=0)
    cluster = Cluster(node=node, nodes=[node], replica_n=1)
    server = SimpleNamespace(
        holder=holder, logger=NopLogger(), cluster=cluster, client=None,
    )
    cfg_kw.setdefault("single_process", 1)
    backend = CollectiveBackend(server, CollectiveConfig(**cfg_kw))
    return backend, server


def _plant(holder, n_shards=4, rows=(1, 2, 3)):
    from pilosa_tpu.constants import SHARD_WIDTH

    idx = holder.create_index_if_not_exists("ci")
    idx.create_field_if_not_exists("f")
    rng = np.random.default_rng(7)
    exp = {}
    for row in rows:
        cols = []
        for s in range(n_shards):
            local = np.flatnonzero(rng.random(2048) < 0.1)
            cols.extend(int(s * SHARD_WIDTH + c) for c in local)
        idx.field("f").import_bits([row] * len(cols), cols)
        exp[row] = set(cols)
    return idx, exp


def _call(q):
    from pilosa_tpu.pql.parser import parse

    return parse(q).calls[0].children[0]


@pytest.fixture
def holder():
    from pilosa_tpu.core.holder import Holder

    h = Holder(None)
    h.open()
    yield h
    h.close()


def test_single_process_active_requires_single_node(holder):
    backend, server = _pod(holder)
    try:
        assert backend.active()
        server.cluster.nodes.append(Node(id="n1", process_idx=None))
        # Two nodes, one process: remote shards would read as silently
        # empty — the plane must refuse.
        assert not backend.active()
    finally:
        backend.close()


def test_respellings_share_descriptor_sig_and_program(holder):
    """Satellite: the descriptor signature is the CANONICAL plan
    signature, so commutative respellings share one collective
    descriptor signature and ONE compiled collective program."""
    _, exp = _plant(holder)
    backend, _ = _pod(holder)
    try:
        a = _call("Count(Intersect(Row(f=1), Row(f=2)))")
        b = _call("Count(Intersect(Row(f=2), Row(f=1)))")
        assert backend._call_sig("ci", a) == backend._call_sig("ci", b)
        want = len(exp[1] & exp[2])
        assert backend.count("ci", a) == want
        assert backend.count("ci", b) == want
        count_fns = [k for k in backend._fn_cache if k[0] == "count"]
        assert len(count_fns) == 1, count_fns
    finally:
        backend.close()


def test_count_batch_is_one_entry(holder):
    """A batch of N same-signature queries costs ONE collective entry
    (one seq slot, one barrier, one SPMD program), with duplicates
    deduped inside the program and fanned back out."""
    _, exp = _plant(holder)
    backend, _ = _pod(holder)
    try:
        c12 = _call("Count(Intersect(Row(f=1), Row(f=2)))")
        c13 = _call("Count(Intersect(Row(f=1), Row(f=3)))")
        got = backend.count_batch("ci", [c12, c13, c12, c13])
        assert got == [len(exp[1] & exp[2]), len(exp[1] & exp[3])] * 2
        assert backend.counters["entries"] == 1
        assert backend.counters["batched_entries"] == 4
        assert backend.counters["batched_launches"] == 1
    finally:
        backend.close()


def test_resident_stack_delta_refresh(holder):
    """A write to a resident plane refreshes it by a scattered delta
    (dirty-word journal), not a full re-assembly — and the refreshed
    count is bit-exact."""
    idx, exp = _plant(holder)
    backend, _ = _pod(holder)
    try:
        c = _call("Count(Intersect(Row(f=1), Row(f=2)))")
        assert backend.count("ci", c) == len(exp[1] & exp[2])
        full0 = backend.counters["full_refreshes"]
        assert backend.count("ci", c) == len(exp[1] & exp[2])
        assert backend.counters["resident_hits"] >= 2  # warm: no refresh
        assert backend.counters["full_refreshes"] == full0
        # One-bit write: delta path, not re-assembly.
        idx.field("f").import_bits([1], [5])
        exp[1].add(5)
        assert backend.count("ci", c) == len(exp[1] & exp[2])
        assert backend.counters["delta_hits"] >= 1
        assert backend.counters["full_refreshes"] == full0
    finally:
        backend.close()


def test_resident_stack_delta_disabled(holder):
    """delta-max-fraction=0 turns deltas off: every staleness is a full
    re-assembly (the escape hatch), still bit-exact."""
    idx, exp = _plant(holder)
    backend, _ = _pod(holder, delta_max_fraction=0.0)
    try:
        c = _call("Count(Intersect(Row(f=1), Row(f=2)))")
        assert backend.count("ci", c) == len(exp[1] & exp[2])
        full0 = backend.counters["full_refreshes"]
        idx.field("f").import_bits([1], [5])
        exp[1].add(5)
        assert backend.count("ci", c) == len(exp[1] & exp[2])
        assert backend.counters["delta_hits"] == 0
        assert backend.counters["full_refreshes"] > full0
    finally:
        backend.close()


def test_bsi_stack_resident_across_queries(holder):
    """The BSI plane stack is resident: a repeat Sum re-uses the cached
    (D+1, S, W) stack instead of re-walking containers."""
    from pilosa_tpu.core.field import FieldOptions

    idx, _ = _plant(holder)
    idx.create_field_if_not_exists(
        "v", FieldOptions(type="int", min=0, max=255))
    for col, val in [(3, 10), (9, 20), (700, 30)]:
        idx.field("v").set_value(col, val)
    backend, _ = _pod(holder)
    try:
        depth = idx.field("v").bsi_group("v").bit_depth()
        counts = backend.bsi_val_count("ci", "v", "sum", depth)
        full0 = backend.counters["full_refreshes"]
        counts2 = backend.bsi_val_count("ci", "v", "sum", depth)
        assert list(counts) == list(counts2)
        assert backend.counters["full_refreshes"] == full0
        assert backend.counters["resident_hits"] >= 1
    finally:
        backend.close()


def test_delete_recreate_never_aliases_resident_planes(holder):
    """Satellite: the incarnation half of the fingerprint means a
    deleted-and-recreated index whose fresh generation counters climb
    back can never alias the old index's resident planes (the hazard
    the plane-assembly comment warned about; now asserted)."""
    from pilosa_tpu.constants import SHARD_WIDTH

    idx, exp = _plant(holder, n_shards=2)
    backend, _ = _pod(holder)
    try:
        c = _call("Count(Intersect(Row(f=1), Row(f=2)))")
        old = backend.count("ci", c)
        assert old == len(exp[1] & exp[2]) and old > 0
        holder.delete_index("ci")
        idx = holder.create_index_if_not_exists("ci")
        idx.create_field_if_not_exists("f")
        # Fresh data: rows 1 and 2 share exactly one column, imported
        # with enough bits that bare generation counters climb back
        # toward cached values.
        cols1 = [1, 9, SHARD_WIDTH + 4]
        cols2 = [9, 70, SHARD_WIDTH + 8]
        idx.field("f").import_bits([1] * len(cols1), cols1)
        idx.field("f").import_bits([2] * len(cols2), cols2)
        got = backend.count("ci", _call("Count(Intersect(Row(f=1), Row(f=2)))"))
        assert got == 1, got  # the old answer would be `old`
    finally:
        backend.close()


def test_enter_refuses_epoch_divergence(holder):
    """Epoch-aware membership: a peer whose routing epoch diverges from
    the descriptor's refuses BEFORE computing (the leader's fan-out
    fallback serves the query under its own epoch gates)."""
    _plant(holder)
    backend, server = _pod(holder)
    try:
        c = _call("Count(Row(f=1))")
        desc = backend._descriptor("count", "ci", queries=[str(c)],
                                   sig=backend._call_sig("ci", c))
        desc["seq"] = 1
        desc["epoch"] = server.cluster.routing_epoch + 3  # leader is ahead
        with pytest.raises(CollectiveUnavailable, match="epoch") as ei:
            backend._enter(desc)
        assert ei.value.reason == "epoch"
        assert backend.counters["stale_epoch_refusals"] == 1
        # Topology churn must NOT advance the plane breaker.
        assert backend.health.plane_state() == "closed"
    finally:
        backend.close()


def test_enter_discards_result_when_epoch_advances_mid_execution(holder):
    """A cutover committing while planes are being assembled discards
    the collective result (post-commit GC may have read a moved shard
    as silently empty) — the leader re-runs through the fan-out."""
    _plant(holder)
    backend, server = _pod(holder)
    try:
        c = _call("Count(Row(f=1))")
        desc = backend._descriptor("count", "ci", queries=[str(c)],
                                   sig=backend._call_sig("ci", c))
        desc["seq"] = 1
        orig = backend._run_count

        def bump_then_run(*a, **kw):
            server.cluster.routing_epoch += 1
            return orig(*a, **kw)

        backend._run_count = bump_then_run
        with pytest.raises(CollectiveUnavailable, match="advanced") as ei:
            backend._enter(desc)
        assert ei.value.reason == "epoch"
        assert backend.counters["epoch_rechecks"] == 1
    finally:
        backend.close()


def test_placement_follows_committed_cutover():
    """Mid-rebalance, a committed cutover's shard routes to its NEW
    owner in the descriptor placement — the refreshed-descriptor half
    of the acceptance criterion (the stale-view halves are covered by
    ownership verification + the epoch gates)."""
    nodes = [Node(id="n0", process_idx=0), Node(id="n1", process_idx=1)]
    c = Cluster(node=nodes[0], nodes=nodes, replica_n=1, hasher=ModHasher())
    before = placement(c, "i", 4, 2)
    # n0 leaves the cluster: its shards migrate to n1; one cutover has
    # committed so far.
    moved = before[0][0]
    c.begin_rebalance([nodes[1]])
    c.apply_cutover("i", moved)
    after = placement(c, "i", 4, 2)
    assert moved in after[1] and moved not in after[0]
    # Everything else stays put mid-job (no holes).
    assert sorted(after[0] + after[1]) == list(range(4))


def test_barrier_failpoint_opens_breaker_then_recovers(holder):
    """Chaos ladder: barrier failures open the plane breaker after
    `collective-breaker-failures`; once open, queries short-circuit
    INSTANTLY (no barrier wait); after the fault clears, the half-open
    probe query re-closes the plane."""
    from pilosa_tpu import failpoints
    from pilosa_tpu.cluster.health import ResilienceConfig
    from pilosa_tpu.parallel.device_health import CollectivePlaneHealth

    _, exp = _plant(holder)
    backend, _ = _pod(holder)
    clock = [1000.0]
    backend.health = CollectivePlaneHealth(
        ResilienceConfig(collective_breaker_failures=2,
                         collective_breaker_backoff=1.0).validate(),
        clock=lambda: clock[0])
    try:
        c = _call("Count(Intersect(Row(f=1), Row(f=2)))")
        want = len(exp[1] & exp[2])
        assert backend.count("ci", c) == want
        failpoints.configure("collective-barrier", "error")
        for _ in range(2):
            with pytest.raises(CollectiveUnavailable) as ei:
                backend.count("ci", c)
            assert ei.value.reason == "barrier-timeout"
        assert backend.counters["barrier_timeouts"] == 2
        assert backend.health.plane_state() == "open"
        # Open plane: instant refusal, no barrier wait, no seq burned.
        seq_before = backend._local_seq
        with pytest.raises(CollectiveUnavailable) as ei:
            backend.count("ci", c)
        assert ei.value.reason == "breaker-open"
        assert backend._local_seq == seq_before
        assert backend.counters["breaker_short_circuits"] == 1
        # Fault clears; after the backoff the next query is the probe
        # and re-closes the plane.
        failpoints.reset()
        clock[0] += 10.0
        assert backend.count("ci", c) == want
        assert backend.health.plane_state() == "closed"
    finally:
        failpoints.reset()
        backend.close()


def test_mesh_width_never_aliases_resident_planes(holder):
    """Review regression: the resident-cache key carries the mesh width.
    n_shards=4 pads to k=4 at BOTH mesh_devices=4 and =2, so without the
    width in the key the second count would resident-hit the 4-device
    layout's array — a silently wrong device layout (and a fabricated
    bench scaling curve)."""
    _, exp = _plant(holder)
    backend, _ = _pod(holder)
    try:
        c = _call("Count(Intersect(Row(f=1), Row(f=2)))")
        want = len(exp[1] & exp[2])
        backend.mesh_devices = 4
        assert backend.count("ci", c) == want
        full0 = backend.counters["full_refreshes"]
        backend.mesh_devices = 2
        assert backend.count("ci", c) == want
        assert backend.counters["full_refreshes"] > full0
    finally:
        backend.close()


def test_allow_never_orphans_plane_probe_on_blocked_slice():
    """Review regression: allow() must due-check EVERY breaker before
    claiming any probe — a plane probe claimed and then short-circuited
    by a still-backed-off slice would expire as a failure and double the
    plane backoff from short-circuits alone."""
    from pilosa_tpu.cluster.health import ResilienceConfig
    from pilosa_tpu.parallel.device_health import CollectivePlaneHealth

    clock = [0.0]
    h = CollectivePlaneHealth(
        ResilienceConfig(collective_breaker_failures=1,
                         collective_breaker_backoff=2.0).validate(),
        clock=lambda: clock[0])
    h.record_failure("runtime")  # t=0: plane opens
    clock[0] = 1.0
    h.record_failure("broadcast", [1])  # t=1: slice 1 opens
    clock[0] = 2.5  # plane due (>= 2.0), slice NOT due (>= 3.0)
    assert not h.allow([0, 1])
    assert h.plane_state() == "open"  # no wedged half-open probe
    assert h.counters["plane_probes"] == 0
    assert h.counters["slice_short_circuits"] == 1
    clock[0] = 3.5  # both due: joint probe, one entry resolves both
    assert h.allow([0, 1])
    h.record_success([0, 1])
    assert h.plane_state() == "closed"
    assert h.slice_state(1) == "closed"


def test_broadcast_failure_quarantines_slice():
    from pilosa_tpu.cluster.health import ResilienceConfig
    from pilosa_tpu.parallel.device_health import CollectivePlaneHealth

    clock = [0.0]
    h = CollectivePlaneHealth(
        ResilienceConfig(collective_breaker_failures=1,
                         collective_breaker_backoff=2.0).validate(),
        clock=lambda: clock[0])
    assert h.allow([0, 1])
    h.record_failure("broadcast", [1])
    assert h.slice_state(1) == "open"
    # Plane opened too (failures=1); both short-circuit this entry.
    assert not h.allow([0, 1])
    clock[0] += 2.5
    assert h.allow([0, 1])  # half-open probe claimed
    h.record_success([0, 1])
    assert h.slice_state(1) == "closed"
    assert h.plane_state() == "closed"


def test_executor_falls_back_cleanly_and_counts_reason(holder):
    """A refusing collective plane is a performance event, not an
    availability event: the executor serves the query through the
    fan-out and the refusal reason lands in the collective counter
    group (satellite: fallback-by-reason observability)."""
    from pilosa_tpu.executor import Executor

    _, exp = _plant(holder)
    backend, server = _pod(holder)
    ex = Executor(holder, cluster=server.cluster, workers=0)
    ex.collective = backend
    server.executor = ex
    try:
        def refuse(index, call):
            raise CollectiveUnavailable("mid-rebalance window",
                                        reason="epoch")

        backend.count = refuse
        got = ex.execute("ci", "Count(Intersect(Row(f=1), Row(f=2)))")
        assert got[0] == len(exp[1] & exp[2])
        assert backend.fallbacks == {"epoch": 1}
    finally:
        backend.close()
        ex.close()


def test_collective_eviction_demotes_to_tier(holder):
    """Resident-stack eviction is DEMOTION: past the leaf budget, the
    LRU plane's compressed image lands in the engine's tier manager, and
    the next cold assembly promotes from it instead of walking live
    containers."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.tier import TierConfig

    _, exp = _plant(holder)
    # One (8, W) plane block is 1 MiB on the 8-device mesh: budget fits
    # ~2 planes, so the third leaf evicts the first.
    backend, server = _pod(holder, leaf_budget_bytes=2 * (1 << 20) + (1 << 16))
    ex = Executor(holder, cluster=server.cluster, workers=0,
                  tier_config=TierConfig(host_bytes=1 << 24))
    server.executor = ex
    assert ex.engine.tier is not None
    try:
        for row in (1, 2, 3):
            backend.count("ci", _call(f"Count(Row(f={row}))"))
        assert backend.counters["evictions"] >= 1
        ex.engine.tier.drain()
        assert backend.counters["demotions"] >= 1
        # Re-touch the evicted plane: assembled from the compressed
        # image, bit-exact.
        tp0 = backend.counters["tier_promotes"]
        assert backend.count("ci", _call("Count(Row(f=1))")) == len(exp[1])
        assert backend.counters["tier_promotes"] > tp0
    finally:
        backend.close()
        ex.close()


def test_batcher_coalesces_collective_counts(holder):
    """sched/batcher.py collective_count: concurrent same-signature
    Counts coalesce into ONE backend entry (count_batch), results split
    back bit-exact."""
    import threading

    from pilosa_tpu.sched import MicroBatcher

    _, exp = _plant(holder)
    backend, _ = _pod(holder)
    release = threading.Event()

    def wait_window(group, window):
        release.wait(timeout=10)

    b = MicroBatcher(lambda: None, window=0.001, window_max=0.05,
                     batch_max=8, depth_fn=lambda: 8,
                     wait_window=wait_window)
    try:
        c12 = _call("Count(Intersect(Row(f=1), Row(f=2)))")
        c21 = _call("Count(Intersect(Row(f=2), Row(f=1)))")
        sig = ("sig",)
        results = {}
        threads = []

        def run(i, call):
            results[i] = b.collective_count(backend, "ci", call, sig)

        for i, call in enumerate([c12, c21, c12, c21]):
            t = threading.Thread(target=run, args=(i, call))
            t.start()
            threads.append(t)
        deadline = time.time() + 5
        while b.snapshot()["enqueued"] < 4 and time.time() < deadline:
            time.sleep(0.005)
        release.set()
        for t in threads:
            t.join(timeout=10)
        want = len(exp[1] & exp[2])
        assert results == {0: want, 1: want, 2: want, 3: want}
        assert backend.counters["entries"] == 1  # ONE collective entry
        assert b.snapshot()["coalesced"] == 3
    finally:
        backend.close()


def test_runner_rejects_stale_seq():
    """A gap-skipped descriptor arriving late must be rejected, not
    executed — its barrier peers already timed out."""
    b = _StubBackend()
    r = _Runner(b)
    r.GAP_TIMEOUT = 0.2
    try:
        assert r.submit({"seq": 5}).result(timeout=10) == 50
        fut = r.submit({"seq": 3})  # late arrival from a slow broadcast
        with pytest.raises(CollectiveUnavailable, match="stale"):
            fut.result(timeout=10)
        assert b.order == [5]
    finally:
        r.close()
