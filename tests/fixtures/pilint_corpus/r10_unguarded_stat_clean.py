"""Clean twin of r10_unguarded_stat_bug: every stat rides the
_count_stat guard, whose body is the dominating None-check — a
stats-less holder skips the count instead of crashing the fan-out."""


class Executor:
    def _count_stat(self, name):
        if self.holder.stats is not None:
            self.holder.stats.count(name, 1)

    def _forward_to_all(self, index, c, opt):
        for node in self.cluster.nodes:
            if node.id == self.node.id:
                continue
            if not self.health.allow_request(node.id):
                self._count_stat("WriteForwardSkipped")
                continue
            try:
                self.client.query_node(node, index, str(c), remote=True)
            except Exception as e:
                self.logger.error("forward failed: %s", e)
                self.health.record_failure(node.id)
                self._count_stat("WriteForwardFailed")
            else:
                self.health.record_success(node.id)
