"""Clean twin of r9_device_probe_bug: a side-effect-free _due_locked
pass over every involved breaker runs BEFORE any probe is claimed, so a
short-circuit can never orphan a claimed probe (the shipped
DevicePlaneHealth.plan shape)."""

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class DevicePlaneHealth:
    def plan(self, sig=None):
        now = self.clock()
        with self._mu:
            s = self._sigs.get(sig) if sig is not None else None
            if self._plane.state != CLOSED:
                if (s is not None and s.state != CLOSED
                        and not self._due_locked(s, now)):
                    self.counters["plane_short_circuits"] += 1
                    return "host"
                gate = self._gate_locked(self._plane, now, "plane_probes",
                                         "plane_short_circuits")
                if gate is False:
                    return "host"
                if s is not None and s.state != CLOSED:
                    self._gate_locked(s, now, "sig_probes",
                                      "sig_short_circuits")
                return "device"
            if s is not None:
                if self._gate_locked(s, now, "sig_probes",
                                     "sig_short_circuits") is False:
                    return "shard"
        return "device"

    def _due_locked(self, b, now):
        if b.state == OPEN:
            return now - b.opened_at >= b.backoff
        if b.state == HALF_OPEN:
            return now - b.probe_at >= self.base
        return True

    def _gate_locked(self, b, now, probes_key, short_key):
        if b.state == CLOSED:
            return None
        if b.state == OPEN and now - b.opened_at >= b.backoff:
            b.state = HALF_OPEN
            b.probe_at = now
            self.counters[probes_key] += 1
            return True
        self.counters[short_key] += 1
        return False
