"""Clean twin of r9_collective_probe_bug: two passes — a side-effect-
free due check over EVERY breaker first, probe claims second (the
shipped CollectivePlaneHealth.allow shape)."""

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CollectivePlaneHealth:
    def allow(self, slices):
        now = self.clock()
        with self._mu:
            if not self._due_locked(self._plane, now):
                self.counters["plane_short_circuits"] += 1
                return False
            open_slices = []
            for p in slices:
                s = self._slices.get(int(p))
                if s is None or s.state == CLOSED:
                    continue
                if not self._due_locked(s, now):
                    self.counters["slice_short_circuits"] += 1
                    return False
                open_slices.append(s)
            gate = self._gate_locked(self._plane, now, "plane_probes",
                                     "plane_short_circuits")
            if gate is False:
                return False
            for s in open_slices:
                self._gate_locked(s, now, "slice_probes",
                                  "slice_short_circuits")
        return True

    def _due_locked(self, b, now):
        if b.state == OPEN:
            return now - b.opened_at >= b.backoff
        if b.state == HALF_OPEN:
            return now - b.probe_at >= self.base
        return True

    def _gate_locked(self, b, now, probes_key, short_key):
        if b.state == CLOSED:
            return None
        if b.state == OPEN and now - b.opened_at >= b.backoff:
            b.state = HALF_OPEN
            b.probe_at = now
            self.counters[probes_key] += 1
            return True
        self.counters[short_key] += 1
        return False
