"""Reverted fix (PR 9 round 5): the batched count dispatched through
_device_call but materialized the device array OUTSIDE it. jax
dispatches asynchronously, so a real device fault surfaces at the
np.asarray — as a raw XlaRuntimeError that bypasses classification, the
breakers, and the executor's fallback ladder entirely."""

import numpy as np


class Engine:
    def count_batch(self, index, calls, shards):
        sig = ("count_batch", len(calls), len(shards))
        fn = self._fn_build(self._count_fns, sig, self._build)
        leaves = self._leaf_tensor(index, calls, shards)
        arr = self._device_call(sig, lambda: fn(leaves))
        return np.asarray(arr)[: len(calls)]
