"""Clean twin of r3_helper_blocking_bug: capture under the mutex,
persist after releasing it (the docs/tiered-storage.md split)."""

import os


class DemoteWorker:
    def commit(self, entry):
        with self._mu:
            self._queue.append(entry)
            payload = self._encode()
            self._notify()
        self._persist(payload)

    def _persist(self, payload):
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def _notify(self):
        self._dirty = True

    def _encode(self):
        return "state"
