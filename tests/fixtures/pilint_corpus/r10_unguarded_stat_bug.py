"""Reverted fix (PR 12 crash class): the write-forward fan-out counted
breaker short-circuits straight through self.holder.stats — and
library embedders run Holder(None), so the DEGRADED path (peer down,
breaker open) crashed on the counter that was supposed to observe it."""


class Executor:
    def _forward_to_all(self, index, c, opt):
        for node in self.cluster.nodes:
            if node.id == self.node.id:
                continue
            if not self.health.allow_request(node.id):
                self.holder.stats.count("WriteForwardSkipped", 1)
                continue
            try:
                self.client.query_node(node, index, str(c), remote=True)
            except Exception as e:
                self.logger.error("forward failed: %s", e)
                self.health.record_failure(node.id)
                self.holder.stats.count("WriteForwardFailed", 1)
            else:
                self.health.record_success(node.id)
