"""Reverted fix (DevicePlaneHealth.plan): with the plane breaker open
and the query's signature also quarantined, the pre-fix gate claimed the
PLANE's half-open probe first and only then discovered the signature was
still inside its own backoff — short-circuiting to "host" with the probe
already claimed. The orphaned probe expired as a failure and doubled the
plane backoff from short-circuits alone."""

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class DevicePlaneHealth:
    def plan(self, sig=None):
        now = self.clock()
        with self._mu:
            s = self._sigs.get(sig) if sig is not None else None
            if self._plane.state != CLOSED:
                gate = self._gate_locked(self._plane, now, "plane_probes",
                                         "plane_short_circuits")
                if gate is False:
                    return "host"
                if s is not None and s.state != CLOSED:
                    g2 = self._gate_locked(s, now, "sig_probes",
                                           "sig_short_circuits")
                    if g2 is False:
                        # Plane probe already claimed: orphaned.
                        return "host"
                return "device"
            if s is not None:
                if self._gate_locked(s, now, "sig_probes",
                                     "sig_short_circuits") is False:
                    return "shard"
        return "device"

    def _gate_locked(self, b, now, probes_key, short_key):
        if b.state == CLOSED:
            return None
        if b.state == OPEN and now - b.opened_at >= b.backoff:
            b.state = HALF_OPEN
            b.probe_at = now
            self.counters[probes_key] += 1
            return True
        self.counters[short_key] += 1
        return False
