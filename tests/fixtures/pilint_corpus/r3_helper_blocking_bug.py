"""Reverted fix (PR 8 review-round class): the demote commit holds the
worker mutex while a helper persists state — the fsync and rename are
one call deep, invisible to a lexical per-file rule, and every reader
of this fragment's queue stalls behind the disk flush."""

import os


class DemoteWorker:
    def commit(self, entry):
        with self._mu:
            self._queue.append(entry)
            self._persist()
            self._notify()

    def _persist(self):
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self._encode())
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def _notify(self):
        self._dirty = True

    def _encode(self):
        return "state"
