"""Clean twin of r8_unguarded_materialization_bug: materialize INSIDE
the guard thunk, where a device fault is classified, recorded into the
breakers, and re-raised typed for the executor's ladder."""

import numpy as np


class Engine:
    def count_batch(self, index, calls, shards):
        sig = ("count_batch", len(calls), len(shards))
        fn = self._fn_build(self._count_fns, sig, self._build)
        leaves = self._leaf_tensor(index, calls, shards)
        return self._device_call(
            sig, lambda: np.asarray(fn(leaves))[: len(calls)])
