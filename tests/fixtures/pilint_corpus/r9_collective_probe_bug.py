"""Reverted fix (CollectivePlaneHealth.allow — the same claim-before-
check bug as the device plane, shipped and fixed independently): the
leader-side gate claimed the plane's half-open probe, then walked the
participating slices and returned False on the first slice still inside
its backoff. Every such short-circuit orphaned the plane probe, which
expired as a failure — the plane's backoff doubled without a single
real collective entry."""

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CollectivePlaneHealth:
    def allow(self, slices):
        now = self.clock()
        with self._mu:
            gate = self._gate_locked(self._plane, now, "plane_probes",
                                     "plane_short_circuits")
            if gate is False:
                return False
            for p in slices:
                s = self._slices.get(int(p))
                if s is None or s.state == CLOSED:
                    continue
                g2 = self._gate_locked(s, now, "slice_probes",
                                       "slice_short_circuits")
                if g2 is False:
                    # Plane probe (and earlier slices') already claimed.
                    return False
        return True

    def _gate_locked(self, b, now, probes_key, short_key):
        if b.state == CLOSED:
            return None
        if b.state == OPEN and now - b.opened_at >= b.backoff:
            b.state = HALF_OPEN
            b.probe_at = now
            self.counters[probes_key] += 1
            return True
        self.counters[short_key] += 1
        return False
