"""Clean twin of r11_config_drift_bug: same dataclass, linted against a
surface corpus that carries every spelling of both fields — parser,
dump, env, flag mapping, CLI flag, and the subsystem doc."""

from dataclasses import dataclass


@dataclass
class EngineConfig:
    gather_workers: int = 0
    plan_cache: int = 1
