"""Reverted fix (the config-plane drift R11 exists for, as shipped in
this PR's own sweep): `plan_cache` was parseable from TOML, settable by
env and flag — but absent from the to_toml dump and the subsystem doc,
so a resolved config written back out silently DROPPED the knob and no
operator could discover it. The test supplies a surface corpus missing
exactly those two spellings."""

from dataclasses import dataclass


@dataclass
class EngineConfig:
    gather_workers: int = 0
    plan_cache: int = 1
