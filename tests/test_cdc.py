"""CDC change streams, point-in-time reads, and standing queries.

The contract under test (docs/cdc.md): every WAL append gets a dense
per-index position that survives background-snapshot WAL splicing,
restarts, and kill -9 on either side of the stream; a cursor behind
retention gets a typed 410 and re-seeds from compressed fragment
images; at-position queries are bit-exact with a fragment that stopped
writing there; standing queries re-push only when a write actually
changed their answer.
"""

import base64
import json
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request
import zlib

import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.cdc import CdcConfig
from pilosa_tpu.cdc.log import (CdcRecord, decode_cdc_records,
                                encode_cdc_record)
from pilosa_tpu.errors import CdcGoneError, QueryError
from pilosa_tpu.server.server import Server
from pilosa_tpu.storage.bitmap import Bitmap, replay_ops
from pilosa_tpu.storage.logscan import scan_log


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_server(tmp_path, name="node0", open_http=False, **cdc_kw):
    cdc_kw.setdefault("enabled", True)
    cdc_kw.setdefault("standing_interval", 0)  # tests drive evaluate_once
    s = Server(data_dir=str(tmp_path / name), cache_flush_interval=0,
               cdc_config=CdcConfig(**cdc_kw))
    if open_http:
        s.open()
    else:
        s.holder.open()
    return s


def _close(s):
    s.cdc.close()
    s.holder.close()


@pytest.fixture
def server(tmp_path):
    s = make_server(tmp_path)
    yield s
    _close(s)


def frag_of(s, index="i", field="f", shard=0):
    return s.holder.index(index).fields[field].views["standard"] \
        .fragments[shard]


# -------------------------------------------------------------- log scan


def test_logscan_chunk_boundary_tear(tmp_path):
    """A record spanning a chunk boundary decodes whole; a torn tail
    truncates at the last record boundary — with a chunk size small
    enough that every record straddles at least one boundary."""
    path = str(tmp_path / "log")
    frames = [encode_cdc_record(CdcRecord(i + 1, "idx", "f", "standard",
                                          i, b"op" * (5 + i)))
              for i in range(9)]
    with open(path, "wb") as f:
        for fr in frames:
            f.write(fr)
        f.write(frames[0][: len(frames[0]) - 3])  # torn tail
    got = []
    res = scan_log(path, decode_cdc_records, chunk_size=7,
                   on_record=got.append)
    assert res.records == 9 and res.truncated
    assert [r.position for r in got] == list(range(1, 10))
    assert os.path.getsize(path) == sum(len(fr) for fr in frames)
    # A second scan of the truncated file is clean and identical.
    res2 = scan_log(path, decode_cdc_records, chunk_size=7)
    assert res2.records == 9 and not res2.truncated


# ----------------------------------------------------- positions + stream


def test_positions_dense_and_stream_matches_wal(server):
    s = server
    idx = s.holder.create_index("i")
    idx.create_field("f")
    for col in range(20):
        s.api.query("i", f"Set({col}, f=1)")
    log = s.cdc.log("i")
    assert log.last_pos == 20
    data, nxt, inc = s.cdc.stream("i", 0, None, timeout=0)
    recs = [r for r, _ in decode_cdc_records(data)]
    assert [r.position for r in recs] == list(range(1, 21))
    assert nxt == 20 and inc == log.incarnation
    # Replaying the streamed op bytes reproduces the fragment exactly.
    bm = Bitmap()
    for r in recs:
        assert (r.field, r.view, r.shard) == ("f", "standard", 0)
        replay_ops(bm, r.ops)
    assert bm.to_bytes() == frag_of(s).storage.to_bytes()
    # Resume from a mid-stream cursor: exactly the remainder, no overlap.
    data2, nxt2, _ = s.cdc.stream("i", 7, inc, timeout=0)
    assert [r.position for r, _ in decode_cdc_records(data2)] == \
        list(range(8, 21))
    # Bounded chunks still end on a record boundary with >= 1 record.
    data3, nxt3, _ = s.cdc.stream("i", 0, inc, timeout=0, max_bytes=1)
    assert [r.position for r, _ in decode_cdc_records(data3)] == [1]
    assert nxt3 == 1
    # At the head an expired long-poll returns empty with the cursor.
    data4, nxt4, _ = s.cdc.stream("i", 20, inc, timeout=0.05)
    assert data4 == b"" and nxt4 == 20


def test_long_poll_wakes_on_append(server):
    s = server
    idx = s.holder.create_index("i")
    idx.create_field("f")
    out = {}

    def consume():
        out["r"] = s.cdc.stream("i", 0, None, timeout=10)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.1)
    s.api.query("i", "Set(3, f=1)")
    t.join(timeout=10)
    assert not t.is_alive()
    data, nxt, _ = out["r"]
    assert nxt == 1
    assert [r.position for r, _ in decode_cdc_records(data)] == [1]


def test_retention_fold_410_and_bootstrap_bit_exact(tmp_path):
    """Crossing retention folds the oldest records into base images; a
    cursor behind the fold 410s and the bootstrap images + remaining
    stream reproduce the live fragment byte-for-byte."""
    s = make_server(tmp_path, retention_ops=8)
    try:
        idx = s.holder.create_index("i")
        idx.create_field("f")
        for col in range(30):
            s.api.query("i", f"Set({col}, f=1)")
        log = s.cdc.log("i")
        assert log.compactions >= 1 and log.base_pos > 0
        assert log.ops < 30  # the prefix really left the log
        with pytest.raises(CdcGoneError) as ei:
            s.cdc.stream("i", 0, None, timeout=0)
        assert ei.value.first == log.base_pos + 1
        assert ei.value.last == 30
        boot = s.cdc.bootstrap("i")
        assert boot["incarnation"] == log.incarnation
        bm = Bitmap()
        for fr in boot["fragments"]:
            assert fr["position"] == 30
            bm = Bitmap.from_bytes(zlib.decompress(
                base64.b64decode(fr["data"])))
        data, _nxt, _ = s.cdc.stream("i", boot["from"], None, timeout=0)
        for r, _ in decode_cdc_records(data):
            replay_ops(bm, r.ops)  # overlap applies idempotently
        assert bm.to_bytes() == frag_of(s).storage.to_bytes()
    finally:
        _close(s)


def test_parked_long_poll_410s_when_fold_passes_cursor(tmp_path):
    """A reader parked in the long-poll wait re-validates its cursor
    after waking: the append that wakes it can trigger compaction that
    folds positions past the cursor IN THE SAME lock hold. Reading on
    from the rebased offsets would silently skip the folded span (or
    jump the cursor to last_pos with no data) — the reader must get the
    typed 410 and re-seed via bootstrap, never a silent gap."""
    s = make_server(tmp_path, retention_ops=1)
    try:
        idx = s.holder.create_index("i")
        idx.create_field("f")
        s.api.query("i", "Set(1, f=1)")  # pos 1; ops=1, no fold yet
        log = s.cdc.log("i")
        assert log.base_pos == 0 and log.last_pos == 1
        out = {}

        def consume():
            try:
                out["r"] = s.cdc.stream("i", 1, log.incarnation, timeout=10)
            except CdcGoneError as e:
                out["gone"] = e

        t = threading.Thread(target=consume)
        t.start()
        time.sleep(0.2)  # reader parked at the head (cursor == last_pos)
        # ops crosses retention_ops=1: this append folds BOTH records
        # into base images under the same lock hold, then wakes the
        # parked reader — whose entry-time cursor check predates the
        # fold.
        s.api.query("i", "Set(2, f=1)")
        t.join(timeout=10)
        assert not t.is_alive()
        assert log.base_pos == 2  # the fold really passed the cursor
        e = out.get("gone")
        assert e is not None, f"expected 410, got chunk {out.get('r')!r}"
        assert e.last == 2
    finally:
        _close(s)


def test_positions_survive_restart_and_snapshot_splice(tmp_path):
    """The change log is its own artifact: fragment WAL splicing (the
    background snapshotter) and a full server restart neither renumber
    nor drop positions."""
    s = make_server(tmp_path)
    idx = s.holder.create_index("i")
    idx.create_field("f")
    for col in range(10):
        s.api.query("i", f"Set({col}, f=1)")
    frag = frag_of(s)
    frag.snapshot()  # splices the fragment WAL into the container image
    for col in range(10, 15):
        s.api.query("i", f"Set({col}, f=1)")
    log = s.cdc.log("i")
    inc = log.incarnation
    assert log.last_pos == 15
    _close(s)
    s2 = make_server(tmp_path)
    try:
        log2 = s2.cdc.log("i")
        assert log2.incarnation == inc  # same index life
        assert log2.last_pos == 15
        s2.api.query("i", "Set(99, f=1)")
        assert log2.last_pos == 16  # counter continues, no reuse
        data, _nxt, _ = s2.cdc.stream("i", 0, inc, timeout=0)
        assert [r.position for r, _ in decode_cdc_records(data)] == \
            list(range(1, 17))
    finally:
        _close(s2)


def test_background_snapshot_concurrent_with_tailing_consumer(server):
    """A tailing consumer sees a dense, loss-free stream while the
    background snapshotter splices the fragment WAL under the writes."""
    s = server
    idx = s.holder.create_index("i")
    idx.create_field("f")
    s.api.query("i", "Set(0, f=1)")
    frag = frag_of(s)
    frag.max_op_n = 16  # force many background snapshots
    n = 300
    seen = []
    bm = Bitmap()
    done = threading.Event()

    def consume():
        cur, inc = 0, None
        while seen[-1:] != [n]:
            data, cur, inc = s.cdc.stream("i", cur, inc, timeout=5)
            for r, _ in decode_cdc_records(data):
                seen.append(r.position)
                replay_ops(bm, r.ops)
        done.set()

    t = threading.Thread(target=consume)
    t.start()
    for col in range(1, n):
        frag.set_bit(1, col)
    assert done.wait(timeout=60)
    t.join(timeout=10)
    assert seen == list(range(1, n + 1))  # dense: no gap, no renumber
    # Quiesce any in-flight background snapshot before comparing bytes.
    frag.snapshot()
    assert bm.to_bytes() == frag.storage.to_bytes()


def test_index_recreate_fresh_incarnation_410(server):
    s = server
    idx = s.holder.create_index("i")
    idx.create_field("f")
    s.api.query("i", "Set(1, f=1)")
    inc = s.cdc.log("i").incarnation
    s.holder.delete_index("i")
    idx = s.holder.create_index("i")
    idx.create_field("f")
    s.api.query("i", "Set(2, f=1)")
    log = s.cdc.log("i")
    assert log.incarnation != inc
    assert log.last_pos == 1  # fresh sequence, new life
    with pytest.raises(CdcGoneError):
        s.cdc.stream("i", 1, inc, timeout=0)  # stale-life cursor
    # Without the incarnation pin the cursor is accepted — that is
    # exactly why consumers must echo the header back.
    data, nxt, _ = s.cdc.stream("i", 0, None, timeout=0)
    assert nxt == 1


# ------------------------------------------------------ point-in-time reads


def test_at_position_reads_bit_exact(tmp_path):
    """An at-position query equals the answer a frozen twin gave at that
    position — across several checkpoints, after more writes, and after
    a fold moved part of the history into base images."""
    s = make_server(tmp_path, retention_ops=64, pit_cache=4)
    try:
        idx = s.holder.create_index("i")
        idx.create_field("f")
        checkpoints = {}  # position -> frozen Row columns
        for col in range(40):
            s.api.query("i", f"Set({col}, f=1)")
            if col % 10 == 9:
                pos = s.cdc.log("i").last_pos
                checkpoints[pos] = list(
                    s.api.query("i", "Row(f=1)")[0].columns())
        for pos, frozen in checkpoints.items():
            got = s.api.query("i", "Row(f=1)", at_position=pos)
            assert list(got[0].columns()) == frozen, pos
            cnt = s.api.query("i", "Count(Row(f=1))", at_position=pos)
            assert cnt[0] == len(frozen)
        # Materialized twin is byte-exact, not just answer-exact.
        pos = max(checkpoints)
        assert pos == s.cdc.log("i").last_pos
        hist = s.cdc.historical_fragment("i", "f", "standard", 0, pos)
        assert hist.storage.to_bytes() == frag_of(s).storage.to_bytes()
        # LRU stays bounded and serves repeats from cache.
        hits0 = s.cdc.pit.hits
        s.api.query("i", "Row(f=1)", at_position=pos)
        assert s.cdc.pit.hits > hits0
        assert len(s.cdc.pit._cache) <= 4
        # Write-only guard and the 410 gate.
        with pytest.raises(QueryError):
            s.api.query("i", "Set(999, f=1)", at_position=pos)
        for _ in range(200):  # push the early history behind the fold
            s.api.query("i", "Set(1000, f=2)")
            s.api.query("i", "Clear(1000, f=2)")
        base = s.cdc.log("i").base_pos
        assert base > min(checkpoints)
        with pytest.raises(CdcGoneError):
            s.api.query("i", "Row(f=1)", at_position=min(checkpoints))
    finally:
        _close(s)


def test_at_position_requires_cdc(tmp_path):
    s = Server(data_dir=str(tmp_path / "plain"), cache_flush_interval=0)
    s.holder.open()
    try:
        idx = s.holder.create_index("i")
        idx.create_field("f")
        with pytest.raises(QueryError, match="cdc.enabled"):
            s.api.query("i", "Row(f=1)", at_position=1)
    finally:
        s.holder.close()


# --------------------------------------------------------- standing queries


def test_standing_register_dedupes_respellings(server):
    s = server
    idx = s.holder.create_index("i")
    idx.create_field("f")
    a, created_a = s.cdc.standing.register(
        "i", "Count(Union(Row(f=1), Row(f=2)))")
    b, created_b = s.cdc.standing.register(
        "i", "Count(Union(Row(f=2), Row(f=1)))")  # commuted operands
    assert created_a and not created_b
    assert a.id == b.id and a is b
    assert len(s.cdc.standing.list()) == 1
    with pytest.raises(QueryError):
        s.cdc.standing.register("i", "Set(1, f=1)")  # writes refused


def test_standing_pushes_only_on_real_change(server):
    s = server
    idx = s.holder.create_index("i")
    idx.create_field("f")
    s.api.query("i", "Set(1, f=1)")
    sq, _ = s.cdc.standing.register("i", "Count(Row(f=1))")
    assert s.cdc.standing.evaluate_once() == 1  # first eval always runs
    assert (sq.version, sq.pushes) == (1, 1)
    assert sq.to_dict()["result"] == 1
    # No writes since: the sweep skips it entirely (no execution).
    assert s.cdc.standing.evaluate_once() == 0
    assert sq.evals == 1
    # A write that does NOT change the answer: re-evaluates (the epoch
    # moved — it cannot know without looking) but does not re-push.
    s.api.query("i", "Set(7, f=2)")
    assert s.cdc.standing.evaluate_once() == 1
    assert sq.stale == 1 and sq.evals == 2
    assert (sq.version, sq.pushes) == (1, 1)
    # A write that changes the answer re-pushes and wakes pollers.
    got = {}

    def poll():
        got["d"] = s.cdc.standing.poll(sq.id, after_version=1, timeout=10)

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(0.05)
    s.api.query("i", "Set(2, f=1)")
    s.cdc.standing.evaluate_once()
    t.join(timeout=10)
    assert not t.is_alive()
    assert got["d"]["version"] == 2 and got["d"]["result"] == 2
    assert (sq.version, sq.pushes, sq.stale) == (2, 2, 2)


# ------------------------------------------------------------- failpoints


def test_cdc_append_fault_assigns_no_position(server):
    """A change-log disk fault surfaces to the writer, but the WAL write
    stands, no position is assigned, and the stream stays dense."""
    s = server
    idx = s.holder.create_index("i")
    idx.create_field("f")
    s.api.query("i", "Set(1, f=1)")
    failpoints.configure("cdc-append", "error", count=1)
    try:
        with pytest.raises(OSError):
            s.api.query("i", "Set(2, f=1)")
    finally:
        failpoints.reset()
    log = s.cdc.log("i")
    assert log.last_pos == 1
    assert s.cdc.counters.get("cdc_append_errors") == 1
    assert frag_of(s).bit(1, 2)  # the WAL write itself acked
    s.api.query("i", "Set(3, f=1)")
    data, _nxt, _ = s.cdc.stream("i", 0, None, timeout=0)
    assert [r.position for r, _ in decode_cdc_records(data)] == [1, 2]


def test_cdc_deliver_and_bootstrap_faults(server):
    s = server
    idx = s.holder.create_index("i")
    idx.create_field("f")
    s.api.query("i", "Set(1, f=1)")
    failpoints.configure("cdc-deliver", "error", count=1)
    try:
        with pytest.raises(OSError):
            s.cdc.stream("i", 0, None, timeout=0)
    finally:
        failpoints.reset()
    failpoints.configure("cdc-snapshot-bootstrap", "error", count=1)
    try:
        with pytest.raises(OSError):
            s.cdc.bootstrap("i")
    finally:
        failpoints.reset()
    # Neither fault poisoned the log: both paths work afterwards.
    data, nxt, _ = s.cdc.stream("i", 0, None, timeout=0)
    assert nxt == 1
    assert len(s.cdc.bootstrap("i")["fragments"]) == 1


# ------------------------------------------------------------ HTTP surface


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read()


def test_http_stream_bootstrap_and_standing(tmp_path):
    s = make_server(tmp_path, open_http=True)
    try:
        base = f"http://localhost:{s.port}"
        s.api.create_index("i")
        s.api.create_field("i", "f")
        for col in range(5):
            s.api.query("i", f"Set({col}, f=1)")
        st, hdr, data = _get(f"{base}/cdc/stream?index=i&from=0&timeout=0")
        assert st == 200
        assert hdr["Content-Type"] == "application/octet-stream"
        assert int(hdr["X-Pilosa-Cdc-Next"]) == 5
        inc = hdr["X-Pilosa-Cdc-Incarnation"]
        assert [r.position for r, _ in decode_cdc_records(data)] == \
            [1, 2, 3, 4, 5]
        # Stale incarnation over HTTP is a typed 410 with resume hints.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/cdc/stream?index=i&from=0&timeout=0"
                 f"&incarnation=not-{inc}")
        assert ei.value.code == 410
        body = json.loads(ei.value.read())
        assert body["incarnation"] == inc and body["last"] == 5
        st, _hdr, data = _get(f"{base}/cdc/bootstrap?index=i")
        boot = json.loads(data)
        assert boot["from"] == 5 and len(boot["fragments"]) == 1
        # at-position over HTTP, header spelling.
        req = urllib.request.Request(
            f"{base}/index/i/query", data=b"Count(Row(f=1))",
            headers={"X-Pilosa-At-Position": "3"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read())["results"][0] == 3
        # Standing lifecycle over HTTP.
        req = urllib.request.Request(
            f"{base}/cdc/standing",
            data=json.dumps({"index": "i",
                             "query": "Count(Row(f=1))"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            reg = json.loads(r.read())
        assert reg["created"]
        s.cdc.standing.evaluate_once()
        st, _hdr, data = _get(
            f"{base}/cdc/standing/{reg['id']}/poll?version=0&timeout=5")
        got = json.loads(data)
        assert got["version"] == 1 and got["result"] == 5
        st, _hdr, data = _get(f"{base}/cdc/standing")
        assert len(json.loads(data)["queries"]) == 1
        req = urllib.request.Request(
            f"{base}/cdc/standing/{reg['id']}", method="DELETE")
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()
        assert s.cdc.standing.list() == []
        # /debug/vars carries the cdc group.
        st, _hdr, data = _get(f"{base}/debug/vars")
        dv = json.loads(data)["cdc"]
        assert dv["indexes"]["i"]["last_pos"] == 5
    finally:
        s.close()


def test_http_cdc_disabled_is_typed_error(tmp_path):
    s = Server(data_dir=str(tmp_path / "off"), cache_flush_interval=0)
    s.open()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://localhost:{s.port}/cdc/stream?index=i&from=0")
        assert ei.value.code == 400
        assert "cdc.enabled" in json.loads(ei.value.read())["error"]
    finally:
        s.close()


# ------------------------------------------------------------ config knobs


def test_cdc_config_sources(tmp_path, monkeypatch):
    from pilosa_tpu.config import Config

    toml = tmp_path / "c.toml"
    toml.write_text("[cdc]\nenabled = true\nretention-ops = 77\n")
    cfg = Config.load(str(toml))
    assert cfg.cdc.enabled and cfg.cdc.retention_ops == 77
    monkeypatch.setenv("PILOSA_TPU_CDC_RETENTION_OPS", "99")
    cfg = Config.load(str(toml))
    assert cfg.cdc.retention_ops == 99  # env beats file
    cfg = Config.load(str(toml), flags={"cdc_retention_ops": 55,
                                        "cdc_pit_cache": 3})
    assert cfg.cdc.retention_ops == 55 and cfg.cdc.pit_cache == 3
    assert "[cdc]" in cfg.to_toml()
    with pytest.raises(ValueError, match="cdc.pit-cache"):
        CdcConfig(pit_cache=0).validate()


# ----------------------------------------------- kill -9 consumer recovery


CONSUMER = textwrap.dedent("""
    import base64, json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import urllib.request
    from pilosa_tpu.cdc.log import decode_cdc_records
    from pilosa_tpu.storage.bitmap import Bitmap, replay_ops

    url, state_path, target = sys.argv[1], sys.argv[2], int(sys.argv[3])
    cur, bm = 0, Bitmap()
    if os.path.exists(state_path):
        st = json.load(open(state_path))
        cur = st["from"]
        bm = Bitmap.from_bytes(base64.b64decode(st["bitmap"]))
    applied = cur
    while cur < target:
        with urllib.request.urlopen(
                f"{url}/cdc/stream?index=i&from={cur}&timeout=5"
                "&max-bytes=150", timeout=30) as r:
            data = r.read()
            nxt = int(r.headers["X-Pilosa-Cdc-Next"])
        for rec, _ in decode_cdc_records(data):
            assert rec.position == applied + 1, (rec.position, applied)
            replay_ops(bm, rec.ops)
            applied = rec.position
        cur = nxt
        # Cursor and applied state persist as ONE atomic artifact, so a
        # kill -9 between requests can never desync them.
        tmp = state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"from": cur, "bitmap":
                       base64.b64encode(bm.to_bytes()).decode()}, f)
        os.replace(tmp, state_path)
        print(cur, flush=True)
    print("DONE", flush=True)
""")


def test_sigkill_mid_stream_consumer_resumes_loss_free(tmp_path):
    """The resumability contract end to end: a real subprocess consumer
    checkpoints (cursor, applied-state) atomically, is SIGKILLed
    mid-stream, restarts from its checkpoint, and converges to the exact
    live fragment — dense positions prove no record was lost, skipped,
    or double-applied."""
    s = make_server(tmp_path, open_http=True)
    try:
        s.api.create_index("i")
        s.api.create_field("i", "f")
        n = 120
        for col in range(n):
            s.api.query("i", f"Set({col}, f=1)")
        state = str(tmp_path / "consumer.json")
        args = [sys.executable, "-c", CONSUMER,
                f"http://localhost:{s.port}", state, str(n)]
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        child = subprocess.Popen(args, stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE, text=True, env=env)
        acked = 0
        try:
            for line in child.stdout:
                acked = int(line)
                if acked >= 20:
                    break  # mid-stream, checkpoint on disk
        finally:
            child.kill()
            child.wait(timeout=30)
        assert 0 < acked < n
        child = subprocess.Popen(args, stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE, text=True, env=env)
        out, err = child.communicate(timeout=120)
        assert child.returncode == 0, err
        assert "DONE" in out
        st = json.load(open(state))
        assert st["from"] == n
        got = Bitmap.from_bytes(base64.b64decode(st["bitmap"]))
        assert got.to_bytes() == frag_of(s).storage.to_bytes()
    finally:
        s.close()


def test_long_poll_consumer_observes_server_close(tmp_path):
    """Shutdown regression: a consumer parked in the stream long-poll
    must observe Server.close() promptly. close() interrupts the CDC
    log waiters BEFORE joining HTTP handler threads, so shutdown never
    has to wait out a poll timeout — the parked request returns empty
    at its cursor (a normal resumable response, not an error)."""
    s = make_server(tmp_path, open_http=True)
    closed = False
    try:
        s.api.create_index("i")
        s.api.create_field("i", "f")
        s.api.query("i", "Set(1, f=1)")
        base = f"http://localhost:{s.port}"
        out = {}
        started = threading.Event()

        def consume():
            # Parked at the head: nothing past position 1 is coming.
            started.set()
            out["r"] = _get(f"{base}/cdc/stream?index=i&from=1&timeout=60",
                            timeout=90)

        t = threading.Thread(target=consume)
        t.start()
        assert started.wait(5)
        time.sleep(0.3)  # let the request actually park in the wait
        t0 = time.monotonic()
        s.close()
        closed = True
        took = time.monotonic() - t0
        t.join(timeout=30)
        assert not t.is_alive()
        assert took < 15.0, f"close() waited out the long-poll: {took:.1f}s"
        st, hdr, data = out["r"]
        assert st == 200 and data == b""
        assert int(hdr["X-Pilosa-Cdc-Next"]) == 1  # cursor unchanged
    finally:
        if not closed:
            s.close()


def test_bootstrap_racing_compaction_consistent_cut(tmp_path):
    """A bootstrap whose image serialization races the retention fold
    must still hand the consumer a consistent (base image, cut
    position) pair: replaying the stream from the returned cursor over
    the images reproduces the live fragment byte-for-byte, with dense
    positions (no gap) and no double-apply (the workload mixes Set and
    Clear, so a replayed stale record would corrupt the bytes). If the
    fold outruns the pinned cut the consumer sees a clean 410 and
    re-seeds — never a silent gap. The `cdc-snapshot-bootstrap`
    latency failpoint holds the serialization window open while the
    writer forces folds through it."""
    import random

    s = make_server(tmp_path, retention_ops=8)
    try:
        idx = s.holder.create_index("i")
        idx.create_field("f")
        rng = random.Random(1337)  # seed-pinned interleave
        writes = 0

        def write_one():
            nonlocal writes
            col = rng.randrange(64)
            if rng.random() < 0.3:
                s.api.query("i", f"Clear({col}, f=1)")
            else:
                s.api.query("i", f"Set({col}, f=1)")
            writes += 1

        for _ in range(40):
            write_one()
        log = s.cdc.log("i")
        assert log.compactions >= 1  # folds really happen at this scale

        stop = threading.Event()

        def writer():
            while not stop.is_set():
                write_one()
                time.sleep(0.002)

        # Hold each bootstrap's off-lock serialization window open so
        # the writer drives retention folds straight through it.
        failpoints.configure("cdc-snapshot-bootstrap", "latency", arg=150)
        w = threading.Thread(target=writer)
        w.start()
        try:
            boots = [s.cdc.bootstrap("i") for _ in range(3)]
        finally:
            stop.set()
            w.join(timeout=30)
            failpoints.deactivate("cdc-snapshot-bootstrap")
        final = s.cdc.log("i").last_pos
        assert final > 40  # the race window saw live writes
        frag = frag_of(s)
        frag.snapshot()  # quiesce before byte compares
        want = frag.storage.to_bytes()

        for boot in boots:
            bm = Bitmap()
            for fr in boot["fragments"]:
                bm = Bitmap.from_bytes(zlib.decompress(
                    base64.b64decode(fr["data"])))
            cur, inc = boot["from"], boot["incarnation"]
            for _ in range(10):
                try:
                    data, cur, inc = s.cdc.stream("i", cur, inc, timeout=0)
                except CdcGoneError:
                    # The fold outran this cut: typed 410, clean re-seed
                    # — the documented recovery, never a silent gap.
                    boot2 = s.cdc.bootstrap("i")
                    for fr in boot2["fragments"]:
                        bm = Bitmap.from_bytes(zlib.decompress(
                            base64.b64decode(fr["data"])))
                    cur, inc = boot2["from"], boot2["incarnation"]
                    continue
                got = [r.position for r, _ in decode_cdc_records(data)]
                # Dense from the cursor: no gap, no double-delivery.
                assert got == list(range(cur - len(got) + 1, cur + 1))
                for r, _ in decode_cdc_records(data):
                    replay_ops(bm, r.ops)
                if cur == final:
                    break
            assert cur == final
            assert bm.to_bytes() == want
    finally:
        _close(s)
