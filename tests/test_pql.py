"""PQL parser tests (model: the grammar in /root/reference/pql/pql.peg and
parser usage throughout executor_test.go)."""

import pytest

from pilosa_tpu.pql.ast import BETWEEN, Condition, EQ, GT, LTE
from pilosa_tpu.pql.parser import ParseError, parse


def one(q):
    query = parse(q)
    assert len(query.calls) == 1
    return query.calls[0]


def test_row():
    c = one("Row(f=10)")
    assert c.name == "Row"
    assert c.args == {"f": 10}
    assert c.field_arg() == "f"
    assert c.uint_arg("f") == (10, True)


def test_nested_calls():
    c = one("Count(Intersect(Row(a=1), Row(b=2)))")
    assert c.name == "Count"
    inner = c.children[0]
    assert inner.name == "Intersect"
    assert [ch.name for ch in inner.children] == ["Row", "Row"]
    assert inner.children[0].args == {"a": 1}


def test_set():
    c = one("Set(100, f=10)")
    assert c.name == "Set"
    assert c.args == {"_col": 100, "f": 10}


def test_set_with_timestamp():
    c = one("Set(9, f=10, 2016-01-01T00:00)")
    assert c.args == {"_col": 9, "f": 10, "_timestamp": "2016-01-01T00:00"}
    c = one('Set(9, f=10, "2016-01-01T00:00")')
    assert c.args["_timestamp"] == "2016-01-01T00:00"


def test_set_string_col():
    c = one('Set("foo", f=10)')
    assert c.args == {"_col": "foo", "f": 10}


def test_clear():
    c = one("Clear(5, f=3)")
    assert c.name == "Clear"
    assert c.args == {"_col": 5, "f": 3}


def test_set_row_attrs():
    c = one('SetRowAttrs(f, 10, foo="bar", baz=123, active=true, x=null)')
    assert c.args == {
        "_field": "f",
        "_row": 10,
        "foo": "bar",
        "baz": 123,
        "active": True,
        "x": None,
    }


def test_set_column_attrs():
    c = one('SetColumnAttrs(7, foo="bar")')
    assert c.args == {"_col": 7, "foo": "bar"}


def test_topn():
    c = one("TopN(f, n=2)")
    assert c.args == {"_field": "f", "n": 2}
    c = one("TopN(f)")
    assert c.args == {"_field": "f"}


def test_topn_with_src_and_filters():
    c = one('TopN(f, Row(other=10), n=5, attrname="category", attrvalues=[1,2])')
    assert c.args["_field"] == "f"
    assert c.children[0].name == "Row"
    assert c.args["n"] == 5
    assert c.args["attrname"] == "category"
    assert c.args["attrvalues"] == [1, 2]


def test_range_condition():
    c = one("Range(f > 20)")
    assert isinstance(c.args["f"], Condition)
    assert c.args["f"].op == GT
    assert c.args["f"].value == 20


def test_range_between_conditional():
    c = one("Range(10 < f < 20)")
    cond = c.args["f"]
    assert cond.op == BETWEEN
    assert cond.value == [11, 20]
    c = one("Range(10 <= f <= 20)")
    assert c.args["f"].value == [10, 21]


def test_range_between_op():
    c = one("Range(f >< [10, 20])")
    assert c.args["f"].op == BETWEEN
    assert c.args["f"].value == [10, 20]


def test_range_neq_null():
    c = one("Range(f != null)")
    assert c.args["f"].op == "neq"
    assert c.args["f"].value is None


def test_range_timerange():
    c = one("Range(f=1, 2010-01-01T00:00, 2010-01-02T03:00)")
    assert c.args == {
        "f": 1,
        "_start": "2010-01-01T00:00",
        "_end": "2010-01-02T03:00",
    }


def test_multiple_calls():
    q = parse("Set(1, f=1)\nSet(2, f=1) Count(Row(f=1))")
    assert [c.name for c in q.calls] == ["Set", "Set", "Count"]


def test_lists_and_strings():
    c = one('Eq(f=["a", "b", 3, 4.5])')
    assert c.args["f"] == ["a", "b", 3, 4.5]


def test_float_and_negative():
    c = one("Range(f > -10)")
    assert c.args["f"].value == -10
    c = one("X(f=1.5)")
    assert c.args["f"] == 1.5


def test_call_roundtrip_str():
    c = one("Count(Intersect(Row(a=1), Row(b=2)))")
    assert str(c) == "Count(Intersect(Row(a=1), Row(b=2)))"


def test_parse_error():
    with pytest.raises(ParseError):
        parse("Row(f=")
    with pytest.raises(ParseError):
        parse("Row f=1)")


def test_empty_call():
    c = one("Status()")
    assert c.name == "Status"
    assert c.args == {} and c.children == []
