"""B+tree container store tests: mapping semantics vs a dict oracle, and
the full Bitmap test surface running on the B-tree backend (model:
reference enterprise/ btree tests + containers_test.go)."""

import random

import numpy as np
import pytest

from pilosa_tpu.storage import bitmap as bm
from pilosa_tpu.storage.btree_containers import BTreeContainers


def test_btree_vs_dict_oracle():
    rng = random.Random(3)
    tree = BTreeContainers()
    oracle = {}
    for step in range(20000):
        key = rng.randrange(0, 2000)
        op = rng.random()
        if op < 0.6:
            tree[key] = key * 2
            oracle[key] = key * 2
        elif op < 0.8 and oracle:
            k = rng.choice(list(oracle))
            del tree[k]
            del oracle[k]
        else:
            assert (key in tree) == (key in oracle)
            if key in oracle:
                assert tree[key] == oracle[key]
    assert len(tree) == len(oracle)
    assert list(tree) == sorted(oracle)  # in-order iteration
    assert dict(tree.items()) == oracle


def test_btree_ordered_iteration_large():
    tree = BTreeContainers()
    keys = list(range(0, 100000, 7))
    random.Random(1).shuffle(keys)
    for k in keys:
        tree[k] = k
    assert list(tree) == sorted(keys)
    assert tree.last() == (sorted(keys)[-1], sorted(keys)[-1])
    from_5000 = list(tree.iterate_from(5000))
    assert from_5000[0][0] >= 5000


def test_btree_get_missing():
    tree = BTreeContainers()
    tree[5] = "x"
    with pytest.raises(KeyError):
        tree[6]
    with pytest.raises(KeyError):
        del tree[6]
    assert tree.get(6) is None
    assert tree.pop(5) == "x"
    assert len(tree) == 0


@pytest.fixture
def btree_backend():
    bm.set_container_factory(BTreeContainers)
    yield
    bm.set_container_factory(dict)


def test_bitmap_on_btree_backend(btree_backend):
    rng = random.Random(9)
    vals = sorted(rng.sample(range(1 << 22), 5000))
    b = bm.Bitmap(vals)
    assert isinstance(b.containers.store, BTreeContainers)
    assert list(b.slice()) == vals
    # Serialization round-trip through the B-tree backend.
    b2 = bm.Bitmap.from_bytes(b.to_bytes())
    assert b == b2
    # Set algebra.
    other = bm.Bitmap(vals[::2])
    assert b.intersection_count(other) == len(vals[::2])
    assert set(b.difference(other).slice().tolist()) == set(vals[1::2])
    # Mutation + clone keeps the backend.
    c = b.clone()
    assert isinstance(c.containers.store, BTreeContainers)
    assert c.remove(vals[0])
    assert not c.contains(vals[0])
    assert b.contains(vals[0])


def test_fragment_on_btree_backend(btree_backend, tmp_path):
    from pilosa_tpu.core.fragment import Fragment

    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
    f.open()
    f.set_bit(1, 10)
    f.set_bit(1, 20)
    f.set_bit(2, 10)
    assert list(f.row(1).columns()) == [10, 20]
    f.close()
    f2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
    f2.open()
    assert list(f2.row(1).columns()) == [10, 20]
    f2.close()
