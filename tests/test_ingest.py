"""Amortized-ingest tests: bulk WAL records, the snapshot trigger policy,
the background snapshotter (copy-on-write handoff, off-lock I/O, mid-
snapshot write splicing), and the parallel import fan-out.

Crash-safety for the new record types (SIGKILL / injected-crash
subprocess harness) lives in tests/test_durability.py.
"""

import os
import threading
import time

import numpy as np
import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.errors import CorruptFragmentError
from pilosa_tpu.storage import StorageConfig
from pilosa_tpu.storage.bitmap import (
    OP_ADD,
    Bitmap,
    encode_bulk_op,
    encode_op,
)
from pilosa_tpu.storage.snapshotter import Snapshotter


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def make_frag(tmp_path, name="0", **kw):
    f = Fragment(str(tmp_path / "fragments" / name), "i", "f", "standard", 0, **kw)
    f.open()
    return f


# ----------------------------------------------------- bulk WAL record codec


def test_bulk_record_roundtrip_with_point_ops():
    base = Bitmap([1, 2, 3]).to_bytes()
    rec = encode_bulk_op(
        np.array([100, 200, 70_000], dtype=np.uint64),
        np.array([2], dtype=np.uint64),
    )
    out = Bitmap.from_buffer(base + rec + encode_op(OP_ADD, 99))
    assert out.contains(100) and out.contains(70_000) and out.contains(99)
    assert not out.contains(2)
    assert out.op_n == 2  # one bulk record + one point op
    assert out.ops_bytes == len(rec) + 13
    assert out.truncated_bytes == 0


def test_bulk_record_empty_sides():
    base = Bitmap([5]).to_bytes()
    only_adds = encode_bulk_op(np.array([7], dtype=np.uint64), None)
    only_rems = encode_bulk_op(None, np.array([5], dtype=np.uint64))
    out = Bitmap.from_buffer(base + only_adds + only_rems)
    assert out.contains(7) and not out.contains(5)


def test_bulk_record_torn_tail_truncates():
    base = Bitmap([1]).to_bytes()
    good = encode_bulk_op(np.array([50], dtype=np.uint64), None)
    torn = encode_bulk_op(np.array([60, 61], dtype=np.uint64), None)
    for cut in (1, 5, 12, len(torn) - 1):
        out = Bitmap.from_buffer(base + good + torn[:cut])
        assert out.contains(50) and not out.contains(60)
        assert out.valid_len == len(base) + len(good)
        assert out.truncated_bytes == cut


def test_bulk_record_corrupt_final_checksum_truncates():
    base = Bitmap([1]).to_bytes()
    bad = bytearray(encode_bulk_op(np.array([60], dtype=np.uint64), None))
    bad[-1] ^= 0xFF  # flip checksum byte
    out = Bitmap.from_buffer(base + bytes(bad))
    assert not out.contains(60)
    assert out.truncated_bytes == len(bad)


def test_bulk_record_corrupt_mid_log_raises():
    base = Bitmap([1]).to_bytes()
    bad = bytearray(encode_bulk_op(np.array([60], dtype=np.uint64), None))
    bad[9] ^= 0xFF  # flip a payload byte; checksum now fails
    with pytest.raises(CorruptFragmentError, match="mid-log"):
        Bitmap.from_buffer(base + bytes(bad) + encode_op(OP_ADD, 70))


def test_failed_append_truncates_partial_record(tmp_path):
    """A failed append (ENOSPC-style) that left PARTIAL record bytes must
    truncate back to the last whole-record boundary — otherwise the next
    successful append buries the garbage mid-log and reopen quarantines
    the fragment as bit rot."""
    frag = make_frag(tmp_path)
    frag.bulk_import(np.zeros(100, dtype=np.uint64),
                     np.arange(100, dtype=np.uint64))
    good_size = os.path.getsize(frag.path)
    assert good_size == frag.storage_bytes + frag.wal_bytes
    # Simulate the partial flush a failing disk leaves behind.
    rec = encode_bulk_op(np.arange(200, 300, dtype=np.uint64), None)
    frag._wal.write(rec[:11])
    frag._wal.flush()
    frag._truncate_torn_append()
    assert os.path.getsize(frag.path) == good_size
    # Writes keep working on the restored handle; reopen replays clean.
    frag.bulk_import(np.ones(50, dtype=np.uint64),
                     np.arange(50, dtype=np.uint64))
    frag.close()
    frag2 = make_frag(tmp_path)
    assert frag2.row_count(0) == 100 and frag2.row_count(1) == 50
    assert frag2.recovered_tail_bytes == 0
    frag2.close()


# -------------------------------------------------- copy-on-write snapshots


def test_cow_clone_freezes_under_live_writes():
    bm = Bitmap(np.arange(100_000, dtype=np.uint64))
    snap = bm.cow_clone()
    bm.add(500_000)
    bm.remove(5)
    bm.add_many(np.arange(200_000, 201_000, dtype=np.uint64))
    bm.remove_many(np.arange(10, 20, dtype=np.uint64))
    assert snap.contains(5) and snap.contains(15)
    assert not snap.contains(500_000) and not snap.contains(200_500)
    assert bm.contains(500_000) and not bm.contains(5)
    # The clone serializes the frozen state.
    out = Bitmap.from_bytes(snap.to_bytes())
    assert out.count() == 100_000


# -------------------------------------------- amortized fragment bulk writes


def test_bulk_import_appends_wal_instead_of_snapshot(tmp_path):
    frag = make_frag(tmp_path)
    rows = np.repeat(np.arange(4, dtype=np.uint64), 1000)
    cols = np.tile(np.arange(1000, dtype=np.uint64), 4)
    frag.bulk_import(rows, cols)
    # The old path snapshotted (op_n back to 0, file rewritten); the
    # amortized path leaves ONE op-log record.
    assert frag.op_n == 1
    assert frag.wal_bytes > 0
    frag.bulk_import(rows, cols + np.uint64(1000))
    assert frag.op_n == 2
    assert frag.row_count(2) == 2000
    frag.close()
    frag2 = make_frag(tmp_path)
    assert frag2.op_n == 2  # replayed, not folded
    assert frag2.row_count(2) == 2000
    frag2.close()


def test_remove_bulk_roundtrip(tmp_path):
    frag = make_frag(tmp_path)
    rows = np.repeat(np.arange(4, dtype=np.uint64), 100)
    cols = np.tile(np.arange(100, dtype=np.uint64), 4)
    frag.bulk_import(rows, cols)
    frag.remove_bulk(
        np.full(50, 2, dtype=np.uint64), np.arange(50, dtype=np.uint64))
    assert frag.row_count(2) == 50 and frag.row_count(1) == 100
    frag.close()
    frag2 = make_frag(tmp_path)
    assert frag2.row_count(2) == 50 and frag2.row_count(1) == 100
    frag2.close()


def test_import_value_replays_without_snapshot(tmp_path):
    frag = make_frag(tmp_path)
    cols = np.arange(30, dtype=np.uint64)
    frag.import_value(cols, cols * np.uint64(3), 8)
    assert frag.op_n == 1  # one bsi-import record, no snapshot
    # Overwrite some values: clears must replay too.
    frag.import_value(cols[:10], np.full(10, 7, dtype=np.uint64), 8)
    frag.close()
    frag2 = make_frag(tmp_path)
    for c in range(10):
        assert frag2.value(c, 8) == (7, True)
    for c in range(10, 30):
        assert frag2.value(c, 8) == (c * 3, True)
    frag2.close()


def test_row_counts_matches_per_row(tmp_path):
    frag = make_frag(tmp_path)
    rng = np.random.default_rng(3)
    rows = rng.integers(0, 9, 5000).astype(np.uint64)
    cols = rng.integers(0, SHARD_WIDTH, 5000, dtype=np.uint64)
    frag.bulk_import(rows, cols)
    ids = [0, 1, 5, 7, 8, 12]  # 12 is empty
    batched = list(frag.row_counts(ids))
    assert batched == [frag.row_count(r) for r in ids]
    assert frag.row_counts([]).size == 0
    frag.close()


def test_snapshot_due_policy(tmp_path):
    frag = make_frag(
        tmp_path,
        storage_config=StorageConfig(snapshot_ratio=0.5),
    )
    assert not frag.snapshot_due()
    # Below the 1 MiB floor nothing triggers.
    frag.bulk_import(
        np.zeros(100, dtype=np.uint64), np.arange(100, dtype=np.uint64))
    assert not frag.snapshot_due()
    # Force the accounting over ratio x floor: policy fires.
    frag.wal_bytes = StorageConfig.SNAPSHOT_MIN_BASE
    assert frag.snapshot_due()
    frag.snapshot()
    assert frag.wal_bytes == 0 and not frag.snapshot_due()
    # Op-count trigger still applies (the reference's 2000-op threshold).
    frag.op_n = frag.max_op_n
    assert frag.snapshot_due()
    frag.close()

    # ratio=0 disables the byte trigger entirely.
    frag2 = make_frag(
        tmp_path, name="1",
        storage_config=StorageConfig(snapshot_ratio=0),
    )
    frag2.wal_bytes = 1 << 30
    assert not frag2.snapshot_due()
    frag2.close()


def test_storage_config_validation():
    with pytest.raises(ValueError, match="snapshot-ratio"):
        StorageConfig(snapshot_ratio=-1).validate()
    with pytest.raises(ValueError, match="snapshot-interval"):
        StorageConfig(snapshot_interval=-2).validate()
    StorageConfig(snapshot_ratio=0, snapshot_interval=0).validate()


# ------------------------------------------------------ background snapshots


def holder_with_snapshotter(tmp_path, **cfg):
    h = Holder(
        str(tmp_path / "indexes"),
        storage_config=StorageConfig(snapshot_interval=0, **cfg),
    )
    h.open()
    return h


def test_background_snapshot_folds_wal(tmp_path):
    h = holder_with_snapshotter(tmp_path)
    assert h.snapshotter is not None
    fld = h.create_index("t").create_field("f")
    rows = np.repeat(np.arange(4, dtype=np.uint64), 50_000)
    cols = np.tile(np.arange(50_000, dtype=np.uint64), 4)
    fld.import_bits(rows, cols)  # 1.6 MB record > 0.5 * 1 MiB floor
    frag = h.fragment("t", "f", "standard", 0)
    for _ in range(200):
        if h.snapshotter.counters["snapshots_taken"] >= 1:
            break
        time.sleep(0.02)
    assert h.snapshotter.counters["snapshots_taken"] >= 1
    assert frag.wal_bytes == 0 and frag.op_n == 0
    assert frag.row_count(2) == 50_000
    h.close()
    h2 = Holder(str(tmp_path / "indexes")).open()
    assert h2.fragment("t", "f", "standard", 0).row_count(2) == 50_000
    h2.close()


def test_background_snapshot_does_not_block_writers_or_readers(tmp_path):
    """The acceptance gate: with the snapshot's write/fsync phase stalled
    via failpoint, a reader AND a writer (fragment-mutex holder) must
    complete — proof there is no fragment-mutex hold across snapshot
    I/O."""
    h = holder_with_snapshotter(tmp_path)
    fld = h.create_index("t").create_field("f")
    rows = np.repeat(np.arange(4, dtype=np.uint64), 10_000)
    cols = np.tile(np.arange(10_000, dtype=np.uint64), 4)
    fld.import_bits(rows, cols)
    frag = h.fragment("t", "f", "standard", 0)
    before = h.snapshotter.counters["snapshots_taken"]

    failpoints.configure("snapshot-write", "latency", arg=2000)
    frag._request_snapshot()
    # Wait until the snapshot thread is INSIDE the stalled write phase
    # (it popped the queue but hasn't finished).
    for _ in range(100):
        if h.snapshotter.queue_depth() == 0:
            break
        time.sleep(0.01)
    t0 = time.monotonic()
    assert frag.set_bit(99, 123)          # takes the fragment mutex
    assert frag.row_count(2) == 10_000    # lock-free read
    assert frag.bit(99, 123)
    elapsed = time.monotonic() - t0
    assert elapsed < 1.0, f"blocked {elapsed:.2f}s behind snapshot I/O"

    # The snapshot itself completes and the mid-snapshot write survived.
    for _ in range(400):
        if h.snapshotter.counters["snapshots_taken"] > before:
            break
        time.sleep(0.01)
    assert h.snapshotter.counters["snapshots_taken"] > before
    h.close()
    h2 = Holder(str(tmp_path / "indexes")).open()
    f2 = h2.fragment("t", "f", "standard", 0)
    assert f2.bit(99, 123) and f2.row_count(2) == 10_000
    h2.close()


def test_mid_snapshot_writes_splice_onto_new_file(tmp_path):
    """Writes landing between handoff and rename ride the WAL tail onto
    the NEW file: reopening right after the snapshot must see them."""
    h = holder_with_snapshotter(tmp_path)
    fld = h.create_index("t").create_field("f")
    fld.set_bit(1, 1)
    frag = h.fragment("t", "f", "standard", 0)

    failpoints.configure("snapshot-write", "latency", arg=300)
    frag._request_snapshot()
    time.sleep(0.05)  # snapshot thread inside the stalled phase
    for i in range(10):
        frag.set_bit(2, i)  # mid-snapshot writes
    before = h.snapshotter.counters["snapshots_taken"]
    for _ in range(400):
        if h.snapshotter.counters["snapshots_taken"] >= 1 \
                and h.snapshotter.queue_depth() == 0:
            break
        time.sleep(0.01)
    failpoints.reset()
    # WAL tail carries exactly the mid-snapshot ops.
    assert frag.op_n <= 10
    h.close()
    h2 = Holder(str(tmp_path / "indexes")).open()
    f2 = h2.fragment("t", "f", "standard", 0)
    assert f2.bit(1, 1)
    for i in range(10):
        assert f2.bit(2, i), i
    h2.close()


def test_background_snapshot_error_keeps_wal_handle(tmp_path):
    h = holder_with_snapshotter(tmp_path)
    fld = h.create_index("t").create_field("f")
    fld.set_bit(1, 1)
    frag = h.fragment("t", "f", "standard", 0)
    failpoints.configure("snapshot-rename", "error", count=1)
    frag._request_snapshot()
    for _ in range(200):
        if h.snapshotter.counters["snapshot_errors"] >= 1:
            break
        time.sleep(0.01)
    assert h.snapshotter.counters["snapshot_errors"] == 1
    assert not os.path.exists(frag.path + ".snapshotting.bg")
    # Writes keep working and stay durable (WAL handle intact).
    assert frag.set_bit(3, 3)
    h.close()
    h2 = Holder(str(tmp_path / "indexes")).open()
    assert h2.fragment("t", "f", "standard", 0).bit(3, 3)
    h2.close()


def test_inline_snapshot_mid_background_aborts_stale_rewrite(tmp_path):
    """An inline snapshot (replica restore path) racing a stalled
    background snapshot wins: the background rename must abort rather
    than clobber the newer file."""
    h = holder_with_snapshotter(tmp_path)
    fld = h.create_index("t").create_field("f")
    fld.set_bit(1, 1)
    frag = h.fragment("t", "f", "standard", 0)
    failpoints.configure("snapshot-write", "latency", arg=400)
    frag._request_snapshot()
    time.sleep(0.05)
    frag.set_bit(5, 5)
    frag.snapshot()  # inline: folds everything, bumps the seq
    wal_after_inline = frag.wal_bytes
    time.sleep(0.6)  # let the background attempt finish (and abort)
    assert frag.wal_bytes == wal_after_inline  # bg didn't reset accounting
    assert not os.path.exists(frag.path + ".snapshotting.bg")
    assert frag.bit(5, 5) and frag.bit(1, 1)
    h.close()


def test_snapshotter_periodic_sweep(tmp_path):
    h = Holder(
        str(tmp_path / "indexes"),
        storage_config=StorageConfig(snapshot_interval=0.05),
    )
    h.open()
    fld = h.create_index("t").create_field("f")
    fld.set_bit(1, 1)  # tiny WAL: never hits ratio/op triggers
    frag = h.fragment("t", "f", "standard", 0)
    assert frag.wal_bytes > 0
    for _ in range(200):
        if frag.wal_bytes == 0:
            break
        time.sleep(0.02)
    assert frag.wal_bytes == 0, "periodic sweep never snapshotted"
    h.close()


def test_snapshotter_dedup_and_close_drain(tmp_path):
    s = Snapshotter()
    frag = make_frag(tmp_path)
    frag.set_bit(1, 1)
    assert s.enqueue(frag)
    assert not s.enqueue(frag)  # deduplicated while queued
    assert s.queue_depth() == 1
    s.close()  # drains without a running thread
    assert s.queue_depth() == 0
    assert frag.wal_bytes == 0  # the drain snapshotted it
    frag.close()


def test_concurrent_ingest_readers_see_consistent_counts(tmp_path):
    """Satellite: readers racing bulk imports + background snapshots see
    counts that are always one of the acked states (monotone non-
    decreasing for pure-set ingest), never torn garbage."""
    h = holder_with_snapshotter(tmp_path)
    fld = h.create_index("t").create_field("f")
    stop = threading.Event()
    errors = []

    def reader():
        frag = None
        last = 0
        while not stop.is_set():
            frag = frag or h.fragment("t", "f", "standard", 0)
            if frag is None:
                continue
            n = frag.row_count(1)
            if n < last or n % 500:
                errors.append(f"count went {last} -> {n}")
                return
            last = n

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    rows = np.zeros(500, dtype=np.uint64) + 1
    for i in range(20):
        cols = np.arange(i * 500, (i + 1) * 500, dtype=np.uint64)
        fld.import_bits(rows, cols)
        if i % 7 == 0:
            h.fragment("t", "f", "standard", 0)._request_snapshot()
    stop.set()
    t.join(timeout=5)
    assert not errors, errors
    assert h.fragment("t", "f", "standard", 0).row_count(1) == 10_000
    h.close()


# ------------------------------------------------------- parallel fan-out


def test_tolerant_group_fanout_local_only():
    from pilosa_tpu.executor import Executor

    holder = Holder(None)
    holder.open()
    ex = Executor(holder, workers=4)
    applied = []
    ex.tolerant_group_fanout(
        "i", [0, 1, 2, 3], False,
        lambda shard: applied.append(shard),
        lambda node, shard: (_ for _ in ()).throw(AssertionError("no remotes")),
        workers=4,
    )
    assert sorted(applied) == [0, 1, 2, 3]
    ex.close()
    holder.close()


def test_tolerant_group_fanout_surfaces_local_error_after_all():
    from pilosa_tpu.errors import QueryError
    from pilosa_tpu.executor import Executor

    holder = Holder(None)
    holder.open()
    ex = Executor(holder, workers=0)  # serial path
    applied = []

    def apply_local(shard):
        if shard == 1:
            raise QueryError("bad batch")
        applied.append(shard)

    with pytest.raises(QueryError, match="bad batch"):
        ex.tolerant_group_fanout(
            "i", [0, 1, 2], False, apply_local, lambda n, s: None)
    # The other shards still got their data before the error surfaced.
    assert sorted(applied) == [0, 2]
    ex.close()
    holder.close()


def test_key_mode_import_fans_out_across_shards(tmp_path):
    from pilosa_tpu.server.server import Server

    s = Server(data_dir=str(tmp_path / "node"), cache_flush_interval=0,
               member_monitor_interval=0)
    s.open()
    try:
        s.api.create_index("ki", {"keys": True})
        s.api.create_field("ki", "f", {"keys": True})
        n = 40
        row_keys = [f"r{i % 4}" for i in range(n)]
        col_keys = [f"c{i}" for i in range(n)]
        s.api.import_bits("ki", "f", 0, None, None,
                          row_keys=row_keys, column_keys=col_keys)
        assert s.api.import_batches >= 1
        total = s.api.query("ki", "Count(Union(Row(f=r0), Row(f=r1), "
                            "Row(f=r2), Row(f=r3)))")
        assert total[0] == n
    finally:
        s.close()


def test_import_values_key_mode_groups(tmp_path):
    from pilosa_tpu.server.server import Server

    s = Server(data_dir=str(tmp_path / "node"), cache_flush_interval=0,
               member_monitor_interval=0)
    s.open()
    try:
        s.api.create_index("kv", {"keys": True})
        s.api.create_field("kv", "v", {"type": "int", "min": 0, "max": 1000})
        col_keys = [f"c{i}" for i in range(20)]
        s.api.import_values("kv", "v", 0, None, list(range(20)),
                            column_keys=col_keys)
        res = s.api.query("kv", "Sum(field=v)")
        assert res[0].val == sum(range(20))
    finally:
        s.close()


# ------------------------------------------------------------- timestamps


def test_epoch_zero_timestamp_not_dropped(tmp_path):
    from pilosa_tpu.server.api import _to_datetime
    from pilosa_tpu.server.server import Server

    # Epoch-0 is a real timestamp, not "absent".
    assert _to_datetime(0) is not None
    assert _to_datetime(0).year == 1970
    assert _to_datetime(None) is None

    s = Server(data_dir=str(tmp_path / "node"), cache_flush_interval=0,
               member_monitor_interval=0)
    s.open()
    try:
        s.api.create_index("ts")
        s.api.create_field("ts", "t", {"type": "time", "timeQuantum": "Y"})
        # int 0 = epoch-0 nanoseconds: the old `any(t for t in ...)`
        # presence check treated the whole batch as untimestamped.
        s.api.import_bits("ts", "t", 0, [1], [5], timestamps=[0])
        fld = s.holder.field("ts", "t")
        assert "standard_1970" in fld.view_names()
    finally:
        s.close()


# ---------------------------------------------------------------- config


def test_ingest_config_sources(tmp_path, monkeypatch):
    from pilosa_tpu.config import Config
    from pilosa_tpu.ingest import IngestConfig

    toml = tmp_path / "c.toml"
    toml.write_text(
        "[storage]\nsnapshot-ratio = 0.25\nsnapshot-interval = 30.0\n"
        "[ingest]\nimport-workers = 3\n"
    )
    cfg = Config.load(str(toml))
    assert cfg.storage.snapshot_ratio == 0.25
    assert cfg.storage.snapshot_interval == 30.0
    assert cfg.ingest.import_workers == 3
    monkeypatch.setenv("PILOSA_TPU_INGEST_IMPORT_WORKERS", "5")
    monkeypatch.setenv("PILOSA_TPU_STORAGE_SNAPSHOT_RATIO", "0.75")
    cfg = Config.load(str(toml))
    assert cfg.ingest.import_workers == 5  # env beats file
    assert cfg.storage.snapshot_ratio == 0.75
    cfg = Config.load(str(toml), flags={"ingest_import_workers": 7,
                                        "storage_snapshot_interval": 12.5})
    assert cfg.ingest.import_workers == 7  # flags beat env
    assert cfg.storage.snapshot_interval == 12.5
    dumped = cfg.to_toml()
    assert "[ingest]" in dumped and "import-workers = 7" in dumped
    assert "snapshot-ratio" in dumped
    with pytest.raises(ValueError, match="import-workers"):
        IngestConfig(import_workers=0).validate()


def test_debug_vars_ingest_group(tmp_path):
    import json
    import urllib.request

    from pilosa_tpu.server.server import Server

    s = Server(data_dir=str(tmp_path / "node"), cache_flush_interval=0,
               member_monitor_interval=0)
    s.open()
    try:
        s.api.create_index("dv")
        s.api.create_field("dv", "f")
        s.api.import_bits("dv", "f", 0, [1, 1], [2, 3])
        with urllib.request.urlopen(
                f"http://localhost:{s.port}/debug/vars") as r:
            dv = json.load(r)
        ing = dv["ingest"]
        assert ing["import_batches"] >= 1
        assert ing["wal_bytes"] > 0
        for key in ("snapshots_deferred", "snapshots_taken",
                    "snapshot_queue_depth"):
            assert key in ing
    finally:
        s.close()
