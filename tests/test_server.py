"""Server + HTTP + multi-node cluster tests.

Single-node tests drive the Getting Started flow (reference README.md:33-47)
through real HTTP. Multi-node tests boot N in-process nodes on localhost
with static membership and a deterministic ModHasher — the reference's
trick for distributed tests without containers (test/pilosa.go:161-238).
"""

import socket
import time

import pytest

from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server.client import InternalClient
from pilosa_tpu.server.server import Server


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def server(tmp_path):
    s = Server(data_dir=str(tmp_path / "node0"), cache_flush_interval=0)
    s.open()
    yield s
    s.close()


@pytest.fixture
def client():
    return InternalClient()


def host(s):
    return f"localhost:{s.port}"


def test_getting_started_flow(server, client):
    """README stargazer flow: create schema, set bits, query."""
    client.create_index(host(server), "repository")
    client.create_field(host(server), "repository", "stargazer")
    for col in [1, 2, 3]:
        client.query(host(server), "repository", f"Set({col}, stargazer=10)")
    resp = client.query(host(server), "repository", "Row(stargazer=10)")
    assert resp["results"][0]["columns"] == [1, 2, 3]
    resp = client.query(host(server), "repository", "Count(Row(stargazer=10))")
    assert resp["results"][0] == 3
    resp = client.query(
        host(server), "repository", "TopN(stargazer, n=1)"
    )
    assert resp["results"][0] == [{"id": 10, "count": 3}]


def test_schema_and_status_endpoints(server, client):
    client.create_index(host(server), "i1")
    client.create_field(host(server), "i1", "f1")
    schema = client.schema(host(server))
    assert schema[0]["name"] == "i1"
    assert schema[0]["fields"][0]["name"] == "f1"
    status = client.status(host(server))
    assert status["state"] == "NORMAL"
    assert len(status["nodes"]) == 1


def test_http_import(server, client):
    client.create_index(host(server), "imp")
    client.create_field(host(server), "imp", "f")
    bits = [(1, 10), (1, 20), (2, SHARD_WIDTH + 5)]
    client.import_bits(host(server), "imp", "f", bits)
    resp = client.query(host(server), "imp", "Row(f=1)")
    assert resp["results"][0]["columns"] == [10, 20]
    resp = client.query(host(server), "imp", "Row(f=2)")
    assert resp["results"][0]["columns"] == [SHARD_WIDTH + 5]
    assert client.shards_max(host(server)) == {"imp": 1}


def test_http_import_values(server, client):
    client.create_index(host(server), "impv")
    client.create_field(
        host(server), "impv", "v", {"type": "int", "min": 0, "max": 1000}
    )
    client.import_values(host(server), "impv", "v", [(1, 100), (2, 200)])
    resp = client.query(host(server), "impv", "Sum(field=v)")
    assert resp["results"][0] == {"value": 300, "count": 2}


def test_error_responses(server, client):
    from pilosa_tpu.server.client import ClientError

    with pytest.raises(ClientError, match="not found|NotFound"):
        client.query(host(server), "nosuch", "Row(f=1)")


def test_export(server, client):
    client.create_index(host(server), "ex")
    client.create_field(host(server), "ex", "f")
    client.query(host(server), "ex", "Set(7, f=3)")
    import urllib.request

    with urllib.request.urlopen(
        f"http://{host(server)}/export?index=ex&field=f&shard=0"
    ) as resp:
        assert resp.read().decode() == "3,7\n"


# --------------------------------------------------------------- multi-node


@pytest.fixture
def cluster3(tmp_path):
    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        s = Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=port,
            cluster_hosts=hosts,
            hasher=ModHasher(),
            cache_flush_interval=0,
            executor_workers=0,
        )
        s.open()
        servers.append(s)
    yield servers
    for s in servers:
        s.close()


def test_cluster_membership(cluster3):
    for s in cluster3:
        assert len(s.cluster.nodes) == 3
        assert {n.id for n in s.cluster.nodes} == {n.uri for n in s.cluster.nodes}


def test_cluster_schema_broadcast(cluster3, client):
    client.create_index(host(cluster3[0]), "ci")
    client.create_field(host(cluster3[0]), "ci", "f")
    time.sleep(0.1)
    for s in cluster3:
        assert s.holder.index("ci") is not None
        assert s.holder.index("ci").field("f") is not None


def test_cluster_remote_query(cluster3, client):
    """Bits planted across shards; any node answers the full query
    (reference executor_test.go TestExecutor_Execute_Remote_Row)."""
    client.create_index(host(cluster3[0]), "ci")
    client.create_field(host(cluster3[0]), "ci", "f")
    time.sleep(0.1)
    # With ModHasher, shard s lives on node partition(s) % 3 — plant bits in
    # three different shards through node 0; writes route to owners.
    cols = [1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH + 3, 3 * SHARD_WIDTH + 4]
    for col in cols:
        client.query(host(cluster3[0]), "ci", f"Set({col}, f=9)")
    # Shards must be distributed across more than one node.
    owners = {
        cluster3[0].cluster.shard_nodes("ci", s)[0].id for s in range(4)
    }
    assert len(owners) > 1
    for s in cluster3:
        resp = client.query(host(s), "ci", "Row(f=9)")
        assert resp["results"][0]["columns"] == cols
        resp = client.query(host(s), "ci", "Count(Row(f=9))")
        assert resp["results"][0] == 4


def test_cluster_remote_topn(cluster3, client):
    client.create_index(host(cluster3[0]), "ct")
    client.create_field(host(cluster3[0]), "ct", "f")
    time.sleep(0.1)
    for col in [0, 1, SHARD_WIDTH, SHARD_WIDTH + 1, 2 * SHARD_WIDTH]:
        client.query(host(cluster3[0]), "ct", f"Set({col}, f=10)")
    for col in [2, 3]:
        client.query(host(cluster3[0]), "ct", f"Set({col}, f=20)")
    resp = client.query(host(cluster3[1]), "ct", "TopN(f, n=2)")
    assert resp["results"][0] == [
        {"id": 10, "count": 5},
        {"id": 20, "count": 2},
    ]


def test_cluster_sum_remote(cluster3, client):
    client.create_index(host(cluster3[0]), "cs")
    client.create_field(
        host(cluster3[0]), "cs", "v", {"type": "int", "min": 0, "max": 100}
    )
    time.sleep(0.1)
    client.import_values(
        host(cluster3[0]), "cs", "v",
        [(1, 10), (SHARD_WIDTH + 1, 20), (2 * SHARD_WIDTH + 1, 30)],
    )
    resp = client.query(host(cluster3[2]), "cs", "Sum(field=v)")
    assert resp["results"][0] == {"value": 60, "count": 3}


def test_cluster_attr_broadcast(cluster3, client):
    client.create_index(host(cluster3[0]), "ca")
    client.create_field(host(cluster3[0]), "ca", "f")
    time.sleep(0.1)
    client.query(host(cluster3[0]), "ca", 'SetRowAttrs(f, 1, color="red")')
    for s in cluster3:
        assert s.holder.field("ca", "f").row_attr_store.attrs(1) == {"color": "red"}


def test_debug_vars_and_diagnostics(server, client):
    import json
    import urllib.request

    client.create_index(host(server), "dv")
    client.create_field(host(server), "dv", "f")
    client.query(host(server), "dv", "Set(1, f=1)")
    with urllib.request.urlopen(f"http://{host(server)}/debug/vars") as resp:
        snap = json.loads(resp.read())
    assert "counters" in snap and snap["counters"].get("setBit", 0) >= 1
    with urllib.request.urlopen(f"http://{host(server)}/internal/diagnostics") as resp:
        diag = json.loads(resp.read())
    assert diag["numIndexes"] >= 1 and diag["version"]


def test_long_query_logging(tmp_path):
    from pilosa_tpu.logger import BufferLogger
    from pilosa_tpu.server.client import InternalClient

    logger = BufferLogger()
    s = Server(
        data_dir=str(tmp_path / "lq"), cache_flush_interval=0,
        long_query_time=0.000001, logger=logger,
    )
    s.open()
    try:
        c = InternalClient()
        c.create_index(f"localhost:{s.port}", "lq")
        c.create_field(f"localhost:{s.port}", "lq", "f")
        c.query(f"localhost:{s.port}", "lq", "Set(1, f=1)")
        assert any("long-query-time" in line for _, line in logger.lines)
    finally:
        s.close()
