"""Server + HTTP + multi-node cluster tests.

Single-node tests drive the Getting Started flow (reference README.md:33-47)
through real HTTP. Multi-node tests boot N in-process nodes on localhost
with static membership and a deterministic ModHasher — the reference's
trick for distributed tests without containers (test/pilosa.go:161-238).
"""

import os
import socket
import time

import pytest

from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server.client import InternalClient
from pilosa_tpu.server.server import Server


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def server(tmp_path):
    s = Server(data_dir=str(tmp_path / "node0"), cache_flush_interval=0)
    s.open()
    yield s
    s.close()


@pytest.fixture
def client():
    return InternalClient()


def host(s):
    return f"localhost:{s.port}"


def test_getting_started_flow(server, client):
    """README stargazer flow: create schema, set bits, query."""
    client.create_index(host(server), "repository")
    client.create_field(host(server), "repository", "stargazer")
    for col in [1, 2, 3]:
        client.query(host(server), "repository", f"Set({col}, stargazer=10)")
    resp = client.query(host(server), "repository", "Row(stargazer=10)")
    assert resp["results"][0]["columns"] == [1, 2, 3]
    resp = client.query(host(server), "repository", "Count(Row(stargazer=10))")
    assert resp["results"][0] == 3
    resp = client.query(
        host(server), "repository", "TopN(stargazer, n=1)"
    )
    assert resp["results"][0] == [{"id": 10, "count": 3}]


def test_schema_and_status_endpoints(server, client):
    client.create_index(host(server), "i1")
    client.create_field(host(server), "i1", "f1")
    schema = client.schema(host(server))
    assert schema[0]["name"] == "i1"
    assert schema[0]["fields"][0]["name"] == "f1"
    status = client.status(host(server))
    assert status["state"] == "NORMAL"
    assert len(status["nodes"]) == 1


def test_http_import(server, client):
    client.create_index(host(server), "imp")
    client.create_field(host(server), "imp", "f")
    bits = [(1, 10), (1, 20), (2, SHARD_WIDTH + 5)]
    client.import_bits(host(server), "imp", "f", bits)
    resp = client.query(host(server), "imp", "Row(f=1)")
    assert resp["results"][0]["columns"] == [10, 20]
    resp = client.query(host(server), "imp", "Row(f=2)")
    assert resp["results"][0]["columns"] == [SHARD_WIDTH + 5]
    assert client.shards_max(host(server)) == {"imp": 1}


def test_http_import_values(server, client):
    client.create_index(host(server), "impv")
    client.create_field(
        host(server), "impv", "v", {"type": "int", "min": 0, "max": 1000}
    )
    client.import_values(host(server), "impv", "v", [(1, 100), (2, 200)])
    resp = client.query(host(server), "impv", "Sum(field=v)")
    assert resp["results"][0] == {"value": 300, "count": 2}


def test_error_responses(server, client):
    from pilosa_tpu.server.client import ClientError

    with pytest.raises(ClientError, match="not found|NotFound"):
        client.query(host(server), "nosuch", "Row(f=1)")


def test_export(server, client):
    client.create_index(host(server), "ex")
    client.create_field(host(server), "ex", "f")
    client.query(host(server), "ex", "Set(7, f=3)")
    import urllib.request

    with urllib.request.urlopen(
        f"http://{host(server)}/export?index=ex&field=f&shard=0"
    ) as resp:
        assert resp.read().decode() == "3,7\n"


# --------------------------------------------------------------- multi-node


@pytest.fixture
def cluster3(tmp_path):
    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        s = Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=port,
            cluster_hosts=hosts,
            hasher=ModHasher(),
            cache_flush_interval=0,
            executor_workers=0,
        )
        s.open()
        servers.append(s)
    yield servers
    for s in servers:
        s.close()


def test_cluster_membership(cluster3):
    for s in cluster3:
        assert len(s.cluster.nodes) == 3
        assert {n.id for n in s.cluster.nodes} == {n.uri for n in s.cluster.nodes}


def test_cluster_schema_broadcast(cluster3, client):
    client.create_index(host(cluster3[0]), "ci")
    client.create_field(host(cluster3[0]), "ci", "f")
    time.sleep(0.1)
    for s in cluster3:
        assert s.holder.index("ci") is not None
        assert s.holder.index("ci").field("f") is not None


def test_cluster_remote_query(cluster3, client):
    """Bits planted across shards; any node answers the full query
    (reference executor_test.go TestExecutor_Execute_Remote_Row)."""
    client.create_index(host(cluster3[0]), "ci")
    client.create_field(host(cluster3[0]), "ci", "f")
    time.sleep(0.1)
    # With ModHasher, shard s lives on node partition(s) % 3 — plant bits in
    # three different shards through node 0; writes route to owners.
    cols = [1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH + 3, 3 * SHARD_WIDTH + 4]
    for col in cols:
        client.query(host(cluster3[0]), "ci", f"Set({col}, f=9)")
    # Shards must be distributed across more than one node.
    owners = {
        cluster3[0].cluster.shard_nodes("ci", s)[0].id for s in range(4)
    }
    assert len(owners) > 1
    for s in cluster3:
        resp = client.query(host(s), "ci", "Row(f=9)")
        assert resp["results"][0]["columns"] == cols
        resp = client.query(host(s), "ci", "Count(Row(f=9))")
        assert resp["results"][0] == 4


def test_cluster_remote_topn(cluster3, client):
    client.create_index(host(cluster3[0]), "ct")
    client.create_field(host(cluster3[0]), "ct", "f")
    time.sleep(0.1)
    for col in [0, 1, SHARD_WIDTH, SHARD_WIDTH + 1, 2 * SHARD_WIDTH]:
        client.query(host(cluster3[0]), "ct", f"Set({col}, f=10)")
    for col in [2, 3]:
        client.query(host(cluster3[0]), "ct", f"Set({col}, f=20)")
    resp = client.query(host(cluster3[1]), "ct", "TopN(f, n=2)")
    assert resp["results"][0] == [
        {"id": 10, "count": 5},
        {"id": 20, "count": 2},
    ]


def test_cluster_sum_remote(cluster3, client):
    client.create_index(host(cluster3[0]), "cs")
    client.create_field(
        host(cluster3[0]), "cs", "v", {"type": "int", "min": 0, "max": 100}
    )
    time.sleep(0.1)
    client.import_values(
        host(cluster3[0]), "cs", "v",
        [(1, 10), (SHARD_WIDTH + 1, 20), (2 * SHARD_WIDTH + 1, 30)],
    )
    resp = client.query(host(cluster3[2]), "cs", "Sum(field=v)")
    assert resp["results"][0] == {"value": 60, "count": 3}


def test_cluster_attr_broadcast(cluster3, client):
    client.create_index(host(cluster3[0]), "ca")
    client.create_field(host(cluster3[0]), "ca", "f")
    time.sleep(0.1)
    client.query(host(cluster3[0]), "ca", 'SetRowAttrs(f, 1, color="red")')
    for s in cluster3:
        assert s.holder.field("ca", "f").row_attr_store.attrs(1) == {"color": "red"}


def test_debug_vars_and_diagnostics(server, client):
    import json
    import urllib.request

    client.create_index(host(server), "dv")
    client.create_field(host(server), "dv", "f")
    client.query(host(server), "dv", "Set(1, f=1)")
    with urllib.request.urlopen(f"http://{host(server)}/debug/vars") as resp:
        snap = json.loads(resp.read())
    assert "counters" in snap and snap["counters"].get("setBit", 0) >= 1
    with urllib.request.urlopen(f"http://{host(server)}/internal/diagnostics") as resp:
        diag = json.loads(resp.read())
    assert diag["numIndexes"] >= 1 and diag["version"]


def test_debug_threads_and_profile(server):
    import json
    import urllib.request

    with urllib.request.urlopen(f"http://{host(server)}/debug/threads") as resp:
        dump = json.loads(resp.read())
    assert dump["count"] >= 1
    # The serving thread's own stack must be present and show the handler.
    assert any(
        any("handle_debug_threads" in line for line in stack)
        for stack in dump["threads"].values()
    )
    req = urllib.request.Request(
        f"http://{host(server)}/debug/profile?seconds=0.1", method="POST"
    )
    with urllib.request.urlopen(req) as resp:
        prof = json.loads(resp.read())
    assert os.path.isdir(prof["path"])
    # The capture must have written a trace artifact, not just the dir.
    assert any(files for _, _, files in os.walk(prof["path"]))


def test_long_query_logging(tmp_path):
    from pilosa_tpu.logger import BufferLogger
    from pilosa_tpu.server.client import InternalClient

    logger = BufferLogger()
    s = Server(
        data_dir=str(tmp_path / "lq"), cache_flush_interval=0,
        long_query_time=0.000001, logger=logger,
    )
    s.open()
    try:
        c = InternalClient()
        c.create_index(f"localhost:{s.port}", "lq")
        c.create_field(f"localhost:{s.port}", "lq", "f")
        c.query(f"localhost:{s.port}", "lq", "Set(1, f=1)")
        assert any("long-query-time" in line for _, line in logger.lines)
    finally:
        s.close()


def test_cors_preflight_and_header(tmp_path):
    """CORS parity (reference server/handler_test.go:555-581): OPTIONS is 405
    with no allowed origins; with origins configured, preflight is 200 and the
    Access-Control-Allow-Origin header echoes an allowed origin."""
    import urllib.request

    s = Server(data_dir=str(tmp_path / "nc"), cache_flush_interval=0)
    s.open()
    try:
        req = urllib.request.Request(
            f"http://localhost:{s.port}/index/foo/query", method="OPTIONS")
        req.add_header("Origin", "http://test/")
        req.add_header("Access-Control-Request-Method", "POST")
        try:
            urllib.request.urlopen(req)
            assert False, "expected 405"
        except urllib.error.HTTPError as e:
            assert e.code == 405
    finally:
        s.close()

    s = Server(data_dir=str(tmp_path / "c"), cache_flush_interval=0,
               allowed_origins=["http://test/"])
    s.open()
    try:
        req = urllib.request.Request(
            f"http://localhost:{s.port}/index/foo/query", method="OPTIONS")
        req.add_header("Origin", "http://test/")
        req.add_header("Access-Control-Request-Method", "POST")
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
            assert resp.headers["Access-Control-Allow-Origin"] == "http://test/"
        # Header also present on a normal request from an allowed origin.
        req = urllib.request.Request(f"http://localhost:{s.port}/schema")
        req.add_header("Origin", "http://test/")
        with urllib.request.urlopen(req) as resp:
            assert resp.headers["Access-Control-Allow-Origin"] == "http://test/"
        # Disallowed origin: no CORS header.
        req = urllib.request.Request(f"http://localhost:{s.port}/schema")
        req.add_header("Origin", "http://evil/")
        with urllib.request.urlopen(req) as resp:
            assert resp.headers.get("Access-Control-Allow-Origin") is None
    finally:
        s.close()


def test_tls_server(tmp_path, tls_cert):
    """https bind with a self-signed cert (reference server/server.go:203-232);
    internal client with skip_verify talks to it."""
    cert, key = tls_cert
    s = Server(
        data_dir=str(tmp_path / "tls"), cache_flush_interval=0,
        scheme="https", tls_certificate=cert, tls_certificate_key=key,
        tls_skip_verify=True,
    )
    s.open()
    try:
        assert s.node.uri.startswith("https://")
        c = InternalClient(skip_verify=True)
        c.create_index(s.node.uri, "sec")
        c.create_field(s.node.uri, "sec", "f")
        c.query(s.node.uri, "sec", "Set(1, f=1)")
        res = c.query(s.node.uri, "sec", "Count(Row(f=1))")
        assert res["results"][0] == 1
    finally:
        s.close()


def test_tls_requires_cert():
    with pytest.raises(ValueError):
        Server(scheme="https")


def test_tls_static_cluster(tmp_path, tls_cert):
    """Static https cluster with schemeless host entries: the self-entry
    still matches (no phantom node) and peers are dialed over https."""
    cert, key = tls_cert
    ports = [free_port() for _ in range(2)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    try:
        for i, port in enumerate(ports):
            s = Server(
                data_dir=str(tmp_path / f"node{i}"), port=port,
                cluster_hosts=hosts, hasher=ModHasher(),
                cache_flush_interval=0, executor_workers=0,
                scheme="https", tls_certificate=cert,
                tls_certificate_key=key, tls_skip_verify=True,
            )
            s.open()
            servers.append(s)
        for s in servers:
            assert len(s.cluster.nodes) == 2, [n.uri for n in s.cluster.nodes]
            assert all(n.uri.startswith("https://") for n in s.cluster.nodes)
        c = InternalClient(skip_verify=True)
        c.create_index(servers[0].node.uri, "tc")
        c.create_field(servers[0].node.uri, "tc", "f")
        time.sleep(0.1)
        # Bits in two shards: with ModHasher over 2 nodes they land on
        # different owners, forcing node-to-node fan-out over https.
        c.query(servers[0].node.uri, "tc", "Set(1, f=5)")
        c.query(servers[0].node.uri, "tc", f"Set({SHARD_WIDTH + 2}, f=5)")
        for s in servers:
            resp = c.query(s.node.uri, "tc", "Count(Row(f=5))")
            assert resp["results"][0] == 2
    finally:
        for s in servers:
            s.close()


def test_id_mode_import_missing_rows_is_400(server, client):
    """ID-mode import with columnIDs but no rowIDs must 400, not silently
    import nothing."""
    import json as _json
    import urllib.error
    import urllib.request

    client.create_index(host(server), "idm")
    client.create_field(host(server), "idm", "f")
    req = urllib.request.Request(
        f"http://{host(server)}/index/idm/field/f/import",
        data=_json.dumps({"columnIDs": [1, 2, 3]}).encode(), method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req)
    assert ei.value.code == 400
    assert "mismatch" in ei.value.read().decode()


def test_key_import_forwarding_to_translation_primary(tmp_path):
    """Key-mode bit AND value imports against a translation replica are
    forwarded to the primary (reference PrimaryTranslateStore semantics)."""
    primary = Server(data_dir=str(tmp_path / "pri"), cache_flush_interval=0)
    primary.open()
    c = InternalClient()
    try:
        c.create_index(host(primary), "ki", {"keys": True})
        c.create_field(host(primary), "ki", "b", {"keys": True})
        c.create_field(host(primary), "ki", "v", {"type": "int", "min": 0, "max": 100})
        replica = Server(
            data_dir=str(tmp_path / "rep"), cache_flush_interval=0,
            primary_translate_store_url=f"http://{host(primary)}",
        )
        replica.open()
        try:
            assert replica.translate_store.read_only
            # Schema must exist on the replica too (it forwards, but the
            # field lookup happens first).
            c.create_index(host(replica), "ki", {"keys": True})
            c.create_field(host(replica), "ki", "b", {"keys": True})
            c.create_field(host(replica), "ki", "v", {"type": "int", "min": 0, "max": 100})
            c.import_bits(host(replica), "ki", "b", [("r1", "alice"), ("r1", "bob")])
            c.import_values(host(replica), "ki", "v", [("alice", 42), ("bob", 58)])
            resp = c.query(host(primary), "ki", 'Count(Row(b="r1"))')
            assert resp["results"][0] == 2
            resp = c.query(host(primary), "ki", "Sum(field=v)")
            assert resp["results"][0] == {"value": 100, "count": 2}
        finally:
            replica.close()
    finally:
        primary.close()
