"""Online elastic rebalance: live shard migration under concurrent
traffic and faults (cluster/rebalance.py, docs/rebalance.md).

The tier-1 deterministic chaos test joins a node to a serving cluster
while writes and reads keep flowing AND one peer link runs a scripted
seed-pinned brown-out, asserting the rebalance invariants:

  - zero lost acked writes: every Set() that returned success is present
    after the migration (fragment contents identical to the acked set);
  - reads served throughout: correct-or-clean-error, never a wrong count
    from a half-migrated shard;
  - clean failure handling: a source faulted mid-stream aborts the job
    back to the old topology with all data intact, and a coordinator
    that died mid-job resumes from its checkpoint instead of restarting.
"""

import json
import os
import socket
import threading
import time

import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.cluster.node import Cluster, Node
from pilosa_tpu.cluster.rebalance import (
    RebalanceConfig, pack_framed, unpack_framed,
)
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.errors import PilosaError, ShardMovedError
from pilosa_tpu.server.client import ClientError, InternalClient
from pilosa_tpu.server.server import Server

pytestmark = pytest.mark.chaos

N_SHARDS = 4


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def migration_ports(index="rb", n_shards=N_SHARDS):
    """Three free ports whose host:port node ids produce a 2->3 placement
    that actually MOVES shards onto the third node. Node ids are derived
    from random ports, so an arbitrary triple occasionally yields a
    no-op resize — these tests exist to exercise migration, not to win a
    placement lottery."""
    from pilosa_tpu.cluster.hash import partition as partition_of

    def owner(hosts, shard):
        ordered = sorted(hosts)
        return ordered[partition_of(index, shard, 256) % len(ordered)]

    for _ in range(64):
        ports = [free_port() for _ in range(3)]
        hosts = [f"localhost:{p}" for p in ports]
        gains = [sh for sh in range(n_shards)
                 if owner(hosts, sh) == hosts[2]
                 and owner(hosts[:2], sh) != hosts[2]]
        if gains:
            return ports, hosts
    raise RuntimeError("could not find a migrating port triple")


def make_server(tmp_path, name, port, **kw):
    from pilosa_tpu.cluster.health import ResilienceConfig

    kw.setdefault("cache_flush_interval", 0)
    kw.setdefault("member_monitor_interval", 0)
    kw.setdefault("anti_entropy_interval", 0)
    kw.setdefault("executor_workers", 0)
    kw.setdefault("hasher", ModHasher())
    kw.setdefault("rebalance_config", RebalanceConfig(
        catchup_threshold_bytes=256, max_catchup_rounds=8,
        cutover_pause_max=2.0,
    ))
    # Short breaker backoffs + a generous retry budget: the brown-out
    # phase opens breakers, and recovery must not wait out production
    # backoffs (same tuning as the test_chaos harness).
    kw.setdefault("resilience_config", ResilienceConfig(
        breaker_backoff=0.1, breaker_backoff_max=0.5,
        retry_budget=100.0, retry_refill=1.0,
    ))
    s = Server(data_dir=str(tmp_path / name), port=port, **kw)
    s.open()
    return s


def wait_for(cond, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def load_base(client, h0, index="rb", field="f"):
    """Deterministic dataset: one row-1 bit per shard; returns its cols."""
    client.ensure_index(h0, index)
    client.ensure_field(h0, index, field)
    time.sleep(0.05)
    cols = [s * SHARD_WIDTH + 7 for s in range(N_SHARDS)]
    for col in cols:
        client.query(h0, index, f"Set({col}, {field}=1)")
    assert client.query(
        h0, index, f"Count(Row({field}=1))")["results"][0] == N_SHARDS
    return cols


# --------------------------------------------------------------- tier-1 chaos


def test_join_live_writes_brownout(tmp_path):
    """THE rebalance chaos test: a node joins a 2-node serving cluster
    while (a) a writer keeps issuing Set()s, (b) a reader keeps issuing
    Count()s, and (c) one peer link runs a seed-pinned flaky brown-out.
    Asserts zero lost acked writes (final fragment contents == the acked
    set, byte-identically), correct-or-clean-error reads throughout, and
    a completed job on the 3-node topology with data GC'd off the old
    owners."""
    ports, hosts = migration_ports()
    servers = [
        make_server(tmp_path, f"n{i}", ports[i], cluster_hosts=hosts[:2])
        for i in range(2)
    ]
    client = InternalClient(timeout=10.0)
    h0 = servers[0].node.uri
    try:
        load_base(client, h0)

        stop = threading.Event()
        acked = []  # columns whose Set() returned success
        read_stats = {"ok": 0, "err": 0, "wrong": 0}
        writer_client = InternalClient(timeout=10.0)
        reader_client = InternalClient(timeout=10.0)

        def writer():
            col = 100
            while not stop.is_set():
                shard = col % N_SHARDS
                target = shard * SHARD_WIDTH + col
                try:
                    writer_client.query(h0, "rb", f"Set({target}, f=9)")
                    acked.append(target)
                except (ClientError, PilosaError):
                    pass  # not acked: allowed to be absent
                col += 1
                time.sleep(0.002)

        def reader():
            while not stop.is_set():
                try:
                    got = reader_client.query(
                        h0, "rb", "Count(Row(f=1))")["results"][0]
                except (ClientError, PilosaError):
                    read_stats["err"] += 1
                else:
                    if got == N_SHARDS:
                        read_stats["ok"] += 1
                    else:
                        read_stats["wrong"] += 1
                        # Capture the cluster state the wrong count was
                        # served under — it rides the assert message (this
                        # is how the stale-epoch-stamp hole was diagnosed).
                        read_stats.setdefault("wrong_detail", []).append({
                            "got": got,
                            "epochs": {s.node.id: s.cluster.routing_epoch
                                       for s in list(servers)},
                            "mid": {s.node.id: s.cluster.next_nodes is not None
                                    for s in list(servers)},
                        })
                time.sleep(0.002)

        threads = [threading.Thread(target=writer, daemon=True),
                   threading.Thread(target=reader, daemon=True)]
        for t in threads:
            t.start()
        time.sleep(0.1)

        # Scripted brown-out on the second member's links (never the
        # harness -> query-head link), pinned seed for replay.
        failpoints.seed(int(os.environ.get("PILOSA_TPU_CHAOS_SEED", "4211")))
        failpoints.configure(f"client-send@{hosts[1]}", "flaky", arg=0.2)

        # Join node2 mid-brown-out: coordinator runs the live rebalance.
        s2 = make_server(tmp_path, "n2", ports[2], join_addr=h0,
                         is_coordinator=False)
        servers.append(s2)
        assert wait_for(
            lambda: len(servers[0].cluster.nodes) == 3
            and servers[0].cluster.next_nodes is None, timeout=30,
        ), "live rebalance did not complete under brown-out"

        failpoints.reset()
        # Faults cleared: converge routing (breakers re-close on monitor
        # probes / elapsed backoff) before the final verification reads.
        def converged():
            for s in servers:
                s._monitor_members()
            try:
                return client.query(
                    h0, "rb", "Count(Row(f=1))")["results"][0] == N_SHARDS
            except (ClientError, PilosaError):
                return False

        # Generous margin: under lockcheck instrumentation each poll's 3
        # monitor sweeps + probe round-trips slow by several x, and the
        # reader/writer threads are still running.
        assert wait_for(converged, timeout=20)
        time.sleep(0.1)  # a few post-rebalance reads/writes on clean links
        stop.set()
        for t in threads:
            t.join(timeout=5)

        # Reads stayed correct-or-clean-error the whole time.
        assert read_stats["wrong"] == 0, read_stats
        assert read_stats["ok"] > 0, read_stats
        assert len(acked) > 0

        # Zero lost acked writes: the union of row-9 columns across the
        # final owners equals the acked set exactly (byte-identical
        # fragment convergence — no missing bit, no phantom bit beyond
        # unacked writes that may have partially applied).
        got = client.query(h0, "rb", "Row(f=9)")["results"][0]["columns"]
        assert set(acked) <= set(got), (
            f"lost {len(set(acked) - set(got))} acked writes")
        # Whatever extra bits exist came from writes that were issued but
        # errored mid-fanout — they must at least be from the writer's
        # column stream, never corruption.
        assert all(
            c % SHARD_WIDTH >= 100 and (c // SHARD_WIDTH) < N_SHARDS
            for c in set(got) - set(acked))

        # The joiner serves the shards it owns; old owners GC'd theirs.
        for sh in range(N_SHARDS):
            owners = {n.id for n in servers[0].cluster.shard_nodes("rb", sh)}
            for s in servers:
                frag = s.holder.fragment("rb", "f", "standard", sh)
                if s.node.id in owners:
                    assert frag is not None, (s.node.id, sh)
                else:
                    assert frag is None, (s.node.id, sh)
        # The epoch advanced and every node converged on it.
        epochs = {s.cluster.routing_epoch for s in servers}
        assert len(epochs) == 1 and epochs.pop() > 0
        assert servers[0].rebalance_stats.counters["jobs_completed"] == 1
        # Fragments moved whenever placement actually handed the joiner
        # (or anyone) new shards; jump-hash placement over random test
        # ports occasionally moves nothing — then zero moves is correct.
        shards_moved = servers[0].rebalance_stats.counters["shards_cut_over"]
        moved = sum(
            s.rebalance_stats.counters["fragments_moved"] for s in servers)
        assert (moved > 0) == (shards_moved > 0)
    finally:
        failpoints.reset()
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_source_fault_mid_stream_aborts_clean(tmp_path):
    """A source that faults every migration stream aborts the job: the
    cluster reverts to the old topology with all data intact and the
    joiner's half-fetched state cleaned up."""
    ports, hosts = migration_ports()
    servers = [
        make_server(tmp_path, f"n{i}", ports[i], cluster_hosts=hosts[:2])
        for i in range(2)
    ]
    client = InternalClient(timeout=10.0)
    h0 = servers[0].node.uri
    try:
        load_base(client, h0)
        failpoints.configure("migrate-begin", "error",
                             message="injected source fault")
        s2 = make_server(tmp_path, "n2", ports[2], join_addr=h0,
                         is_coordinator=False)
        servers.append(s2)
        assert wait_for(
            lambda: servers[0].rebalance_stats.counters["jobs_aborted"] == 1,
            timeout=30,
        ), "job did not abort on source fault"
        failpoints.reset()
        # Old topology, fully reverted routing, all data still served.
        assert len(servers[0].cluster.nodes) == 2
        assert servers[0].cluster.next_nodes is None
        assert servers[0].cluster.migrated == set()
        assert client.query(
            h0, "rb", "Count(Row(f=1))")["results"][0] == N_SHARDS
        # No source fragment froze (abort pre-cutover): writes still land.
        client.query(h0, "rb", f"Set({2 * SHARD_WIDTH + 99}, f=1)")
        assert client.query(
            h0, "rb", "Count(Row(f=1))")["results"][0] == N_SHARDS + 1
    finally:
        failpoints.reset()
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_coordinator_crash_resumes_from_checkpoint(tmp_path):
    """The job checkpoint makes a rebalance resumable: a 'crashed'
    coordinator (simulated by a checkpoint with no live job) picks the
    job back up with maybe_resume_rebalance() and completes it."""
    ports, hosts = migration_ports()
    servers = [
        make_server(tmp_path, f"n{i}", ports[i], cluster_hosts=hosts[:2])
        for i in range(2)
    ]
    client = InternalClient(timeout=10.0)
    h0 = servers[0].node.uri
    try:
        load_base(client, h0)
        s2 = make_server(tmp_path, "n2", ports[2],
                         cluster_hosts=[hosts[2]], is_coordinator=True)
        servers.append(s2)
        # Simulate the crash artifact: a job checkpoint naming the target
        # topology with nothing committed yet, and no in-memory job.
        new_nodes = [Node(id=h, uri=h).to_dict() for h in hosts]
        state_path = os.path.join(servers[0].data_dir, ".rebalance.json")
        with open(state_path, "w") as f:
            json.dump({"jobID": "deadbeef", "newNodes": new_nodes,
                       "committed": []}, f)
        assert servers[0].maybe_resume_rebalance()
        # Wait on jobs_completed, not just the topology commit: the
        # counter bump happens-after _clear_state in _complete, so the
        # checkpoint assertion below cannot race the cleanup.
        assert wait_for(
            lambda: len(servers[0].cluster.nodes) == 3
            and servers[0].cluster.next_nodes is None
            and servers[0].rebalance_stats.counters.get(
                "jobs_completed", 0) >= 1, timeout=30,
        ), "resumed rebalance did not complete"
        assert servers[0].rebalance_stats.counters["jobs_resumed"] == 1
        assert not os.path.exists(state_path)
        assert client.query(
            h0, "rb", "Count(Row(f=1))")["results"][0] == N_SHARDS
        # Every shard the joiner now owns was actually moved onto it
        # (placement may or may not hand it one of these 4 shards —
        # jump-hash only moves ~1/n of the keyspace).
        owned = [
            sh for sh in range(N_SHARDS)
            if any(n.id == s2.node.id
                   for n in servers[0].cluster.shard_nodes("rb", sh))
        ]
        for sh in owned:
            assert s2.holder.fragment("rb", "f", "standard", sh) is not None
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_resume_skips_committed_shards(tmp_path):
    """A checkpoint with every movable shard already committed completes
    immediately without re-streaming anything."""
    ports = [free_port() for _ in range(2)]
    hosts = [f"localhost:{p}" for p in ports]
    s0 = make_server(tmp_path, "n0", ports[0], cluster_hosts=[hosts[0]])
    servers = [s0]
    client = InternalClient()
    try:
        load_base(client, s0.node.uri)
        s1 = make_server(tmp_path, "n1", ports[1],
                         cluster_hosts=[hosts[1]], is_coordinator=True)
        servers.append(s1)
        committed = [["rb", sh] for sh in range(N_SHARDS)]
        state_path = os.path.join(s0.data_dir, ".rebalance.json")
        new_nodes = [Node(id=h, uri=h).to_dict() for h in hosts]
        with open(state_path, "w") as f:
            json.dump({"jobID": "cafecafe", "newNodes": new_nodes,
                       "committed": committed}, f)
        before = s0.rebalance_stats.counters["bytes_streamed"]
        assert s0.maybe_resume_rebalance()
        assert wait_for(lambda: s0.cluster.next_nodes is None
                        and len(s0.cluster.nodes) == 2
                        and s0.rebalance_stats.counters.get(
                            "jobs_completed", 0) >= 1, timeout=15)
        assert s0.rebalance_stats.counters["bytes_streamed"] == before
        assert not os.path.exists(state_path)
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_fanout_stamps_epoch_of_placement_decision(tmp_path):
    """The remote fan-out must stamp the routing epoch its PLACEMENT
    decision was made under, not the epoch at send time: a cutover
    landing between assign and dispatch advances the local epoch, and a
    current-epoch stamp would slip the stale placement past the
    receiver's 409 gate (it would serve a shard whose fragment it
    already GC'd as silently empty)."""
    ports = [free_port() for _ in range(2)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        make_server(tmp_path, f"n{i}", ports[i], cluster_hosts=hosts)
        for i in range(2)
    ]
    client = InternalClient()
    try:
        load_base(client, servers[0].node.uri)
        ex = servers[0].executor
        # A prior rebalance advanced both nodes to epoch 5.
        servers[0].cluster.routing_epoch = 5
        servers[1].cluster.routing_epoch = 5

        stamped = []
        real_client = ex.client

        class RecordingClient:
            def query_node(self, node, index, query, **kw):
                stamped.append(kw.get("epoch"))
                return real_client.query_node(node, index, query, **kw)

            def __getattr__(self, name):
                return getattr(real_client, name)

        orig_assign = ex._assign_shards

        def assign_then_cutover(*a, **kw):
            out = orig_assign(*a, **kw)
            # A cutover commits right after the placement read.
            servers[0].cluster.routing_epoch += 1
            return out

        ex.client = RecordingClient()
        ex._assign_shards = assign_then_cutover
        try:
            got = client.query(
                servers[0].node.uri, "rb", "Count(Row(f=1))")["results"][0]
        finally:
            ex.client = real_client
            ex._assign_shards = orig_assign
        assert got == N_SHARDS
        assert stamped and all(e == 5 for e in stamped), stamped
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_receiver_gate_treats_unstamped_as_epoch_zero(tmp_path):
    """A remote query with NO X-Pilosa-Epoch stamp was routed by the
    stalest possible placement (a sender that never saw the rebalance):
    a receiver that has advanced past epoch 0 must 409 for a shard it
    does not serve — never read a missing fragment as silently empty."""
    ports = [free_port() for _ in range(2)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        make_server(tmp_path, f"n{i}", ports[i], cluster_hosts=hosts)
        for i in range(2)
    ]
    client = InternalClient()
    try:
        load_base(client, servers[0].node.uri)
        s1 = servers[1]
        not_served = next(
            sh for sh in range(N_SHARDS)
            if all(n.id != s1.node.id
                   for n in s1.cluster.shard_nodes("rb", sh)))
        # A rebalance advanced the receiver's epoch; the sender below
        # never saw it and sends unstamped.
        s1.cluster.routing_epoch = 3
        with pytest.raises(ClientError) as ei:
            client.query_node(
                s1.cluster.node_by_id(s1.node.id), "rb",
                "Count(Row(f=1))", shards=[not_served], remote=True)
        assert getattr(ei.value, "status", 0) == 409, ei.value
        # A shard the receiver DOES serve still answers unstamped
        # requests (single-node tools, older senders).
        served = next(
            sh for sh in range(N_SHARDS)
            if any(n.id == s1.node.id
                   for n in s1.cluster.shard_nodes("rb", sh)))
        res = client.query_node(
            s1.cluster.node_by_id(s1.node.id), "rb",
            "Count(Row(f=1))", shards=[served], remote=True)
        assert res[0] == 1
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_monitor_adopts_missed_complete(tmp_path):
    """A follower that LOST the rebalance-complete broadcast (a brown-out
    can eat all transport retries) converges via the member monitor's
    epoch sync: probing a peer whose /status reports a newer COMMITTED
    routing epoch, it adopts that topology and GCs fragments for shards
    it no longer owns."""
    from pilosa_tpu.cluster.hash import partition as partition_of

    def owner(hosts, shard):
        ordered = sorted(hosts)
        return ordered[partition_of("rb", shard, 256) % len(ordered)]

    # A port triple where the 2->3 transition moves a shard OFF hosts[1]
    # (the follower whose GC the lost broadcast would orphan).
    for _ in range(256):
        ports = [free_port() for _ in range(3)]
        hosts = [f"localhost:{p}" for p in ports]
        lost = [sh for sh in range(N_SHARDS)
                if owner(hosts[:2], sh) == hosts[1]
                and owner(hosts, sh) == hosts[2]]
        if lost:
            break
    else:
        raise RuntimeError("no port triple moves a shard off hosts[1]")

    servers = [
        make_server(tmp_path, f"n{i}", ports[i], cluster_hosts=hosts[:2])
        for i in range(2)
    ]
    client = InternalClient()
    try:
        load_base(client, servers[0].node.uri)
        s1 = servers[1]
        assert s1.holder.fragment("rb", "f", "standard", lost[0]) is not None

        # Simulate the peer having completed a rebalance whose complete
        # broadcast never reached s1: n0 commits the 3-node topology
        # (preserving its coordinator claim, as a real job's new_nodes
        # do) and advances its epoch; s1 still routes on the 2-node view.
        s0 = servers[0]
        s0.cluster.commit_topology(
            [Node(id=h, uri=h, is_coordinator=(h == s0.node.id))
             for h in hosts],
            epoch=s0.cluster.routing_epoch + 1)
        assert len(s1.cluster.nodes) == 2
        assert s1.cluster.routing_epoch < s0.cluster.routing_epoch

        # Adoption is COORDINATOR-only: with n0's claim suppressed, a
        # sweep must NOT adopt (a non-coordinator at a high epoch may
        # just have seen a cutover-commit mid-job and still carry the
        # old nodes list).
        s0_entry = s0.cluster.node_by_id(s0.node.id)
        s0_entry.is_coordinator = False
        s1._monitor_members()
        assert len(s1.cluster.nodes) == 2
        s0_entry.is_coordinator = True

        # One monitor sweep against the coordinator converges it.
        s1._monitor_members()
        assert s1.cluster.routing_epoch == s0.cluster.routing_epoch
        assert len(s1.cluster.nodes) == 3
        for sh in lost:
            assert s1.holder.fragment("rb", "f", "standard", sh) is None, sh
        kept = [sh for sh in range(N_SHARDS)
                if owner(hosts, sh) == hosts[1]]
        for sh in kept:
            assert s1.holder.fragment("rb", "f", "standard", sh) is not None
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


# --------------------------------------------------- follower resize watchdog


def test_follower_watchdog_reverts_when_coordinator_dies(tmp_path):
    """Legacy stop-the-world path: a coordinator that broadcast RESIZING
    and died before delivering instructions must not strand followers —
    the watchdog probes the coordinator and reverts to NORMAL on the old
    topology."""
    ports = [free_port() for _ in range(2)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        make_server(
            tmp_path, f"n{i}", ports[i], cluster_hosts=hosts,
            is_coordinator=(i == 0),
            rebalance_config=RebalanceConfig(follower_timeout=0.2),
        )
        for i in range(2)
    ]
    try:
        follower = next(s for s in servers if not s.node.is_coordinator)
        coordinator = next(s for s in servers if s.node.is_coordinator)
        follower.cluster.node_by_id(coordinator.node.id).is_coordinator = True
        # The coordinator broadcast RESIZING ... then died before any
        # instruction arrived.
        follower.receive_message({
            "type": "cluster-status", "state": "RESIZING",
            "nodes": [n.to_dict() for n in follower.cluster.nodes],
        })
        assert follower.cluster.state == "RESIZING"
        assert follower._resizing_since is not None
        coordinator.close()
        time.sleep(0.25)
        follower._check_resize_watchdog()
        assert follower.cluster.state == "NORMAL"
        assert follower._resizing_since is None
        assert len(follower.cluster.nodes) == 2  # old topology intact
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_follower_watchdog_respects_live_coordinator(tmp_path):
    """A coordinator that is alive and still RESIZING resets the watchdog
    timer instead of being deposed by an impatient follower."""
    ports = [free_port() for _ in range(2)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        make_server(
            tmp_path, f"n{i}", ports[i], cluster_hosts=hosts,
            is_coordinator=(i == 0),
            rebalance_config=RebalanceConfig(follower_timeout=0.1),
        )
        for i in range(2)
    ]
    try:
        follower = next(s for s in servers if not s.node.is_coordinator)
        coordinator = next(s for s in servers if s.node.is_coordinator)
        follower.cluster.node_by_id(coordinator.node.id).is_coordinator = True
        coordinator.cluster.state = "RESIZING"
        follower.receive_message({
            "type": "cluster-status", "state": "RESIZING",
            "nodes": [n.to_dict() for n in follower.cluster.nodes],
        })
        time.sleep(0.15)
        follower._check_resize_watchdog()
        assert follower.cluster.state == "RESIZING"  # job still live
        assert follower._resizing_since is not None
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


# ------------------------------------------------------- routing epoch units


def _cluster_with_cutover(local_id="a"):
    nodes = [Node(id="a", uri="a"), Node(id="b", uri="b")]
    c = Cluster(node=nodes[0], nodes=nodes, hasher=ModHasher())
    new = nodes + [Node(id="c", uri="c")]
    c.begin_rebalance(new)
    return c


def test_routing_epoch_overrides_placement():
    c = _cluster_with_cutover()
    base_epoch = c.routing_epoch
    assert base_epoch > 0
    # Find a shard whose owner changes between topologies.
    moved = None
    for sh in range(16):
        before = [n.id for n in c.shard_nodes("i", sh)]
        c.migrated.add(("i", sh))
        after = [n.id for n in c.shard_nodes("i", sh)]
        c.migrated.discard(("i", sh))
        if before != after:
            moved = sh
            break
    assert moved is not None
    before = [n.id for n in c.shard_nodes("i", moved)]
    c.apply_cutover("i", moved)
    assert c.routing_epoch == base_epoch + 1
    assert [n.id for n in c.shard_nodes("i", moved)] != before
    # Idempotent re-commit (freeze + broadcast) bumps only once.
    c.apply_cutover("i", moved, epoch=c.routing_epoch)
    assert c.routing_epoch == base_epoch + 1
    # Completion collapses the overrides.
    c.commit_topology()
    assert c.next_nodes is None and c.migrated == set()
    assert len(c.nodes) == 3


def test_adoption_loses_to_concurrent_begin():
    """The anti-entropy topology adoption re-validates under the routing
    lock: a rebalance-begin landing between the monitor's probe decision
    and the commit keeps its next_nodes/migrated overrides — a late
    adopt commit wiping them would route cut-over shards back to their
    old owners until the job's complete broadcast."""
    nodes = [Node(id="a", uri="a"), Node(id="b", uri="b")]
    cluster = Cluster(node=nodes[0], nodes=nodes, hasher=ModHasher())
    target = nodes + [Node(id="c", uri="c")]
    # A begin wins the race: overrides installed, epoch merged to 7.
    cluster.begin_rebalance(target, epoch=7)
    cluster.apply_cutover("i", 3)
    # The adoption loses even with a numerically newer epoch: overrides
    # are in flight and must survive.
    assert not cluster.adopt_topology_if_ahead(nodes, 9)
    assert cluster.next_nodes is not None
    assert ("i", 3) in cluster.migrated
    # A caught-up epoch is also a losing race, overrides or not.
    cluster.abort_rebalance()
    assert not cluster.adopt_topology_if_ahead(target,
                                               cluster.routing_epoch)
    # Quiescent and genuinely ahead: the adoption commits.
    epoch = cluster.routing_epoch
    assert cluster.adopt_topology_if_ahead(target, epoch + 1)
    assert cluster.routing_epoch == epoch + 1
    assert [n.id for n in cluster.nodes] == ["a", "b", "c"]
    assert cluster.next_nodes is None


def test_abort_keeps_committed_cutovers():
    c = _cluster_with_cutover()
    c.apply_cutover("i", 3)
    fully = c.abort_rebalance(committed=[("i", 3)])
    assert fully is False
    assert c.migrated == {("i", 3)} and c.next_nodes is not None
    c2 = _cluster_with_cutover()
    assert c2.abort_rebalance(committed=[]) is True
    assert c2.next_nodes is None and c2.migrated == set()


def test_stale_epoch_rejects_unowned_remote_shards():
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.errors import StaleRoutingEpochError
    from pilosa_tpu.executor import ExecOptions, Executor

    holder = Holder(None)
    holder.open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.set_remote_max_shard(7)
    nodes = [Node(id="a", uri="a"), Node(id="b", uri="b")]
    cluster = Cluster(node=nodes[0], nodes=nodes, hasher=ModHasher())
    ex = Executor(holder, cluster=cluster, workers=0)
    # This node stops owning some shard after a (simulated) cutover.
    cluster.begin_rebalance(nodes + [Node(id="c", uri="c")])
    moved = None
    for sh in range(8):
        cluster.migrated.add(("i", sh))
        owned = any(n.id == "a" for n in cluster.shard_nodes("i", sh))
        cluster.migrated.discard(("i", sh))
        if not owned:
            moved = sh
            break
    assert moved is not None
    cluster.apply_cutover("i", moved)
    stale = ExecOptions(remote=True, epoch=cluster.routing_epoch - 1)
    with pytest.raises(StaleRoutingEpochError):
        ex.execute("i", "Count(Row(f=1))", shards=[moved], opt=stale)
    # A request stamped with the CURRENT epoch is served (the executor
    # trusts the sender's shard list, reference executor.go:1476-1480).
    fresh = ExecOptions(remote=True, epoch=cluster.routing_epoch)
    ex.execute("i", "Count(Row(f=1))", shards=[moved], opt=fresh)
    ex.close()
    holder.close()


def test_moved_fragment_rejects_writes(tmp_path):
    from pilosa_tpu.core.fragment import Fragment

    frag = Fragment(str(tmp_path / "frag.0"), "i", "f", "standard", 0)
    frag.open()
    try:
        frag.set_bit(1, 5)
        frag._moved = True
        with pytest.raises(ShardMovedError):
            frag.set_bit(1, 6)
        with pytest.raises(ShardMovedError):
            frag.clear_bit(1, 5)
        import numpy as np

        with pytest.raises(ShardMovedError):
            frag.bulk_import(np.array([1], dtype=np.uint64),
                             np.array([9], dtype=np.uint64))
        # Reads still serve (the source keeps answering until GC).
        assert frag.bit(1, 5)
    finally:
        frag.close()


def test_cutover_write_wait_follows_commit():
    """A write caught in the freeze->commit window re-routes until the
    commit lands instead of failing: tolerant_owner_fanout retries on
    ShardMovedError within cutover_pause_max."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    holder = Holder(None)
    holder.open()
    holder.create_index("i").create_field("f")
    ex = Executor(holder, workers=0)
    ex.cutover_wait = 2.0
    attempts = {"n": 0}

    def local_fn():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise ShardMovedError("i/f/standard/0")

    ex.tolerant_owner_fanout("i", 0, False, local_fn, lambda node: None)
    assert attempts["n"] == 3
    # Past the cap the clean error surfaces.
    ex.cutover_wait = 0.0
    attempts["n"] = -100  # never succeeds within one attempt
    with pytest.raises(ShardMovedError):
        ex.tolerant_owner_fanout(
            "i", 0, False,
            lambda: (_ for _ in ()).throw(ShardMovedError("i")),
            lambda node: None)
    ex.close()
    holder.close()


def test_abort_unfreezes_uncommitted_shards(tmp_path):
    """An abort after a freeze thaws the source's fragments for shards
    whose cutover never committed — routing reverts to this node, and a
    lingering freeze would leave the shard permanently write-dead.
    Committed shards stay frozen (their data moved)."""
    port = free_port()
    s = make_server(tmp_path, "n0", port, cluster_hosts=[f"localhost:{port}"])
    try:
        client = InternalClient()
        load_base(client, s.node.uri)
        s.cluster.begin_rebalance(list(s.cluster.nodes))
        s.migration_source.freeze("rb", 0)
        s.migration_source.freeze("rb", 1)
        frag0 = s.holder.fragment("rb", "f", "standard", 0)
        frag1 = s.holder.fragment("rb", "f", "standard", 1)
        assert frag0._moved and frag1._moved
        with pytest.raises(ShardMovedError):
            frag0.set_bit(9, 1)
        s._handle_rebalance_abort({
            "jobID": "jx", "reason": "test", "committed": [["rb", 1]],
        })
        assert not frag0._moved  # reverted shard thawed: writes flow again
        assert frag0.set_bit(9, 1)
        assert frag1._moved  # committed shard stays frozen
    finally:
        s.close()


def test_complete_thaws_replica_kept_fragments(tmp_path):
    """The coordinator's _complete must thaw fragments still frozen after
    the holder cleaner runs: with replicas >= 2 the coordinator can be a
    migration SOURCE for a shard it keeps owning as a replica — the
    cleaner keeps that fragment, and a lingering _moved flag would leave
    it permanently write-dead. (Followers already thaw the same way in
    _adopt_committed_topology.)"""
    from pilosa_tpu.cluster.rebalance import (RebalanceCoordinator,
                                              RebalanceJob)

    port = free_port()
    s = make_server(tmp_path, "n0", port, cluster_hosts=[f"localhost:{port}"])
    try:
        client = InternalClient()
        load_base(client, s.node.uri)
        s.cluster.begin_rebalance(list(s.cluster.nodes))
        s.migration_source.freeze("rb", 0)
        frag = s.holder.fragment("rb", "f", "standard", 0)
        assert frag._moved
        coord = RebalanceCoordinator(s)
        job = RebalanceJob("jt", list(s.cluster.nodes), moves={})
        coord.job = job
        # The single node keeps owning shard 0 under the new topology, so
        # the cleaner keeps the fragment — exactly the replica-kept shape.
        coord._complete(job)
        assert not frag._moved
        assert frag.set_bit(9, 1)
    finally:
        s.close()


def test_forwarded_execution_rechecks_epoch_after_gather():
    """A cutover committing DURING a forwarded (opt.remote) gather can GC
    a moved shard's fragment mid-read so it reads as silently empty — the
    entry gate in execute_query ran too early to see it. The receiver
    re-checks the routing epoch after the gather and raises
    StaleRoutingEpochError (-> 409, sender gets its free re-route)
    instead of returning a result with a hole. An epoch bump that leaves
    every shard still owned here stays transparent."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.errors import StaleRoutingEpochError
    from pilosa_tpu.executor import ExecOptions, Executor

    holder = Holder(None)
    holder.open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.set_remote_max_shard(7)
    nodes = [Node(id="a", uri="a"), Node(id="b", uri="b")]
    cluster = Cluster(node=nodes[0], nodes=nodes, hasher=ModHasher())
    ex = Executor(holder, cluster=cluster, workers=0)
    cluster.begin_rebalance(nodes + [Node(id="c", uri="c")])
    moved = None
    for sh in range(8):
        cluster.migrated.add(("i", sh))
        owned = any(n.id == "a" for n in cluster.shard_nodes("i", sh))
        cluster.migrated.discard(("i", sh))
        if not owned:
            moved = sh
            break
    assert moved is not None

    pre_epoch = cluster.routing_epoch
    opt = ExecOptions(remote=True, epoch=pre_epoch)

    def gather_racing_cutover(shards_):
        # The cutover moving this very shard off node 'a' commits while
        # the gather is running (post-commit GC could have emptied it).
        cluster.apply_cutover("i", moved)
        return 0

    with pytest.raises(StaleRoutingEpochError):
        ex._fan_out("i", [moved], None, opt,
                    gather_racing_cutover, lambda a, b: a + b)

    # The cutover can also land BEFORE _fan_out but after execute()'s
    # entry gate (during translation, or an earlier call of a multi-call
    # query): the epoch anchor execute() captures before the gate still
    # flags it, where a snapshot taken inside _fan_out would already be
    # post-cutover and wave the hole through.
    opt_anchored = ExecOptions(remote=True, epoch=pre_epoch,
                               entry_epoch=pre_epoch)
    with pytest.raises(StaleRoutingEpochError):
        ex._fan_out("i", [moved], None, opt_anchored,
                    lambda shards_: 0, lambda a, b: a + b)

    # Epoch advanced mid-gather but the shard stayed local: the result is
    # sound and must flow through, no spurious 409.
    kept = next(
        sh for sh in range(8)
        if sh != moved and any(
            n.id == "a" for n in cluster.shard_nodes("i", sh)))
    opt2 = ExecOptions(remote=True, epoch=cluster.routing_epoch)

    def gather_with_unrelated_bump(shards_):
        cluster.routing_epoch += 1
        return 42

    assert ex._fan_out("i", [kept], None, opt2,
                       gather_with_unrelated_bump, lambda a, b: a + b) == 42
    ex.close()
    holder.close()


# ------------------------------------------------------------ health grace


def test_copy_grace_damps_breaker():
    from pilosa_tpu.cluster.health import (
        CLOSED, OPEN, HealthRegistry, ResilienceConfig,
    )

    clock = [0.0]
    reg = HealthRegistry(ResilienceConfig(breaker_failures=1),
                         clock=lambda: clock[0])
    reg.set_copy_grace("peer")
    for _ in range(reg.COPY_GRACE_MULT - 1):
        reg.record_failure("peer")
    assert reg.state("peer") == CLOSED  # graced: not dead yet
    reg.record_failure("peer")
    assert reg.state("peer") == OPEN  # 4x the threshold finally opens
    # Without grace, one failure opens.
    reg.clear_copy_grace()
    reg2 = HealthRegistry(ResilienceConfig(breaker_failures=1),
                          clock=lambda: clock[0])
    reg2.record_failure("peer")
    assert reg2.state("peer") == OPEN
    # Grace expires on its TTL.
    reg3 = HealthRegistry(ResilienceConfig(breaker_failures=1),
                          clock=lambda: clock[0])
    reg3.set_copy_grace("peer", ttl=5.0)
    assert reg3.in_copy_grace("peer")
    clock[0] = 6.0
    assert not reg3.in_copy_grace("peer")


# ------------------------------------------------------------------ framing


def test_migration_frame_roundtrip():
    hdr, payload = unpack_framed(pack_framed({"pos": 42}, b"\x00\x01binary"))
    assert hdr == {"pos": 42} and payload == b"\x00\x01binary"
    with pytest.raises(PilosaError):
        unpack_framed(b"\x01")
    with pytest.raises(PilosaError):
        unpack_framed(pack_framed({"a": 1})[:5])


def test_replay_ops_rejects_torn_stream():
    import numpy as np

    from pilosa_tpu.errors import CorruptFragmentError
    from pilosa_tpu.storage.bitmap import (
        Bitmap, OP_ADD, encode_bulk_op, encode_op, replay_ops,
    )

    b = Bitmap()
    stream = encode_op(OP_ADD, 5) + encode_bulk_op(
        np.array([9, 10], dtype=np.uint64), None)
    replay_ops(b, stream)
    assert b.contains(5) and b.contains(9) and b.contains(10)
    with pytest.raises(CorruptFragmentError):
        replay_ops(Bitmap(), stream[:-3])
