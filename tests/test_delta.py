"""Delta-refresh device caches: dirty-word journal, scattered HBM updates,
mutation-path bump audit, byte-cache accounting, memo epoch fast path.

The tentpole invariant under test: after ANY sequence of writes, a
delta-refreshed resident plane/stack is byte-identical to a full regather
by a fresh engine — including the fallbacks (journal overflow, bulk
mutations, threshold exceeded), which must degrade to the full path, never
to a partial delta.
"""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH, WORDS_PER_ROW
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.fragment import Fragment, WriteEpoch
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.parallel import EngineConfig
from pilosa_tpu.parallel.engine import Leaf, ShardedQueryEngine
from pilosa_tpu.pql.parser import parse


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def plant(holder, n_shards=4, n_rows=4, per_row=300, seed=7):
    idx = holder.create_index_if_not_exists("i")
    fld = idx.create_field_if_not_exists("f")
    rng = np.random.default_rng(seed)
    for row in range(n_rows):
        cols = []
        for s in range(n_shards):
            local = rng.choice(SHARD_WIDTH, size=per_row, replace=False)
            cols.extend(int(s * SHARD_WIDTH + c) for c in local)
        fld.import_bits([row] * len(cols), cols)
    return idx.field("f")


# ------------------------------------------------------------ journal unit


class TestDirtyJournal:
    def test_point_writes_journal_their_words(self):
        f = Fragment(None, "i", "f", "standard", 0)
        f.open()
        g0 = f.generation
        f.set_bit(1, 64 * 3 + 5)
        f.set_bit(1, 64 * 9)
        f.clear_bit(1, 64 * 3 + 5)
        w = f.dirty_words_since(1, g0)
        assert sorted(w.tolist()) == [3, 9]
        # Another row's cached gen sees the churn but no dirty words.
        assert f.dirty_words_since(2, g0).tolist() == []
        # Fully-caught-up generation: empty delta.
        assert f.dirty_words_since(1, f.generation).tolist() == []

    def test_future_generation_refuses(self):
        f = Fragment(None, "i", "f", "standard", 0)
        f.open()
        # A generation from a previous fragment incarnation (reopen resets
        # the counter) must force a full regather, not an empty delta.
        assert f.dirty_words_since(1, f.generation + 5) is None

    def test_overflow_poisons_then_recovers(self):
        f = Fragment(None, "i", "f", "standard", 0, delta_journal_ops=8)
        f.open()
        g0 = f.generation
        for k in range(12):  # > journal bound
            f.set_bit(1, 64 * k)
        assert f.dirty_words_since(1, g0) is None
        # History since the reset IS complete again.
        g1 = f.generation
        f.set_bit(1, 64 * 50)
        assert f.dirty_words_since(1, g1).tolist() == [50]

    def test_hot_word_churn_does_not_overflow(self):
        """The journal is bounded by UNIQUE dirty words: sustained rewrites
        of the same few words (the mixed ingest+serve regime) must not
        trip the overflow reset and force periodic full regathers."""
        f = Fragment(None, "i", "f", "standard", 0, delta_journal_ops=8)
        f.open()
        g0 = f.generation
        for k in range(100):  # 100 writes, 2 unique words
            f.set_bit(1, 64 * (k % 2) + k % 32)
            f.clear_bit(1, 64 * (k % 2) + k % 32)
        w = f.dirty_words_since(1, g0)
        assert w is not None, "hot-word churn overflowed the journal"
        assert sorted(w.tolist()) == [0, 1]

    def test_bulk_import_poisons_touched_rows_only(self):
        f = Fragment(None, "i", "f", "standard", 0, delta_journal_ops=4)
        f.open()
        g0 = f.generation
        f.set_bit(2, 7)
        # 6 positions > journal bound: row 1 gets poisoned, row 2's
        # history must survive.
        f.bulk_import(np.full(6, 1, np.uint64), np.arange(6, dtype=np.uint64))
        assert f.dirty_words_since(1, g0) is None
        assert f.dirty_words_since(2, g0).tolist() == [0]

    def test_read_from_resets_journal(self):
        import io

        src = Fragment(None, "i", "f", "standard", 0)
        src.open()
        src.set_bit(1, 100)
        buf = io.BytesIO()
        src.write_to(buf)
        dst = Fragment(None, "i", "f", "standard", 0)
        dst.open()
        g0 = dst.generation
        dst.set_bit(1, 200)
        buf.seek(0)
        dst.read_from(buf)
        assert dst.dirty_words_since(1, g0) is None

    def test_row_words64_matches_plane(self):
        f = Fragment(None, "i", "f", "standard", 0)
        f.open()
        rng = np.random.default_rng(3)
        for c in rng.integers(0, SHARD_WIDTH, 200):
            f.set_bit(2, int(c))
        plane64 = f.plane_np(2).view(np.uint64)
        idxs = np.unique(rng.integers(0, SHARD_WIDTH // 64, 32))
        np.testing.assert_array_equal(f.row_words64(2, idxs), plane64[idxs])


# ------------------------------------------------- mutation-path bump audit


def _merge_small(frag):
    # Replica diff below MERGE_BULK_THRESHOLD: per-bit set/clear path.
    rows = np.array([1, 1], dtype=np.uint64)
    cols = np.array([10, 11], dtype=np.uint64)
    frag.merge_block(0, [(rows, cols), (rows, cols)])


def _merge_bulk(frag):
    # Diff above MERGE_BULK_THRESHOLD: storage-level scatter path.
    n = Fragment.MERGE_BULK_THRESHOLD + 8
    rows = np.full(n, 1, dtype=np.uint64)
    cols = np.arange(n, dtype=np.uint64)
    frag.merge_block(0, [(rows, cols), (rows, cols)])


def _read_from(frag):
    import io

    src = Fragment(None, "i", "f", "standard", 0)
    src.open()
    src.set_bit(3, 123)
    buf = io.BytesIO()
    src.write_to(buf)
    buf.seek(0)
    frag.read_from(buf)


MUTATIONS = {
    "set_bit": lambda f: f.set_bit(1, 500),
    "clear_bit": lambda f: f.clear_bit(0, 0),  # row 0 bit 0 pre-planted
    "set_value": lambda f: f.set_value(3, 8, 77),
    "bulk_import": lambda f: f.bulk_import(
        np.array([2, 2], np.uint64), np.array([5, 6], np.uint64)),
    "import_value": lambda f: f.import_value(
        np.array([9], np.uint64), np.array([41], np.uint64), 8),
    "merge_block_small": _merge_small,
    "merge_block_bulk": _merge_bulk,
    "read_from": _read_from,
}


@pytest.mark.parametrize("name", sorted(MUTATIONS))
def test_every_mutation_path_bumps_generation_and_epoch(name):
    """A mutation path that skips the generation or epoch bump serves a
    stale delta silently — this audit pins all of them (fragment.py's two
    generation += 1 sites plus every caller of _invalidate_row)."""
    epoch = WriteEpoch()
    f = Fragment(None, "i", "f", "standard", 0, epoch=epoch)
    f.open()
    f.set_bit(0, 0)  # seed so clear_bit actually clears
    g0, e0 = f.generation, epoch.value
    MUTATIONS[name](f)
    assert f.generation > g0, f"{name} did not bump generation"
    assert epoch.value > e0, f"{name} did not bump write epoch"


# ---------------------------------------------------- engine delta refresh


def _full_leaf(holder, leaf, shards):
    """Ground-truth plane assembly straight from storage."""
    bufs = []
    for s in shards:
        frag = holder.fragment("i", leaf.field, leaf.view, s)
        bufs.append(
            frag.plane_np(leaf.row) if frag is not None
            else np.zeros(WORDS_PER_ROW, np.uint32))
    return np.stack(bufs)


def test_single_set_refreshes_leaf_via_delta(holder):
    """ISSUE acceptance: one set() on a resident leaf refreshes the cached
    plane via the delta path — counter-proven (leaf_delta_hits > 0, bytes
    moved KiB-scale vs the multi-MiB full plane)."""
    fld = plant(holder)
    engine = ShardedQueryEngine(holder)
    shards = list(range(4))
    call = parse("Count(Intersect(Row(f=0), Row(f=1)))").calls[0].children[0]
    before = engine.count("i", call, shards)
    full_bytes = engine.counters["full_refresh_bytes"]
    assert full_bytes >= 2 * 4 * WORDS_PER_ROW * 4  # two multi-MiB planes

    col = 3 * SHARD_WIDTH + 4321
    assert fld.set_bit(0, col)
    after = engine.count("i", call, shards)
    assert engine.counters["leaf_delta_hits"] > 0
    assert engine.counters["full_refresh_bytes"] == full_bytes  # no full walk
    assert engine.counters["delta_bytes"] <= 1024  # vs MiB-scale planes
    want = before + (1 if holder.fragment("i", "f", "standard", 3).bit(1, col)
                     else 0)
    assert after == want
    # The refreshed cached plane is byte-identical to a storage regather.
    leaf = Leaf("f", "standard", 0)
    arr = np.asarray(engine._gather_leaf("i", leaf, tuple(shards)))
    np.testing.assert_array_equal(arr[:4], _full_leaf(holder, leaf, shards))


def test_single_set_refreshes_stack_via_delta(holder):
    fld = plant(holder)
    engine = ShardedQueryEngine(holder)
    shards = list(range(4))
    calls = [parse(f"Intersect(Row(f={a}), Row(f={b}))").calls[0]
             for a, b in [(0, 1), (1, 2), (2, 3)]]
    engine.count_batch("i", calls, shards)
    full_bytes = engine.counters["full_refresh_bytes"]
    assert fld.set_bit(2, 2 * SHARD_WIDTH + 99)
    got = engine.count_batch("i", calls, shards)
    assert engine.counters["stack_delta_hits"] > 0
    assert engine.counters["full_refresh_bytes"] == full_bytes
    singles = [
        int(np.bitwise_count(np.bitwise_and(
            _full_leaf(holder, Leaf("f", "standard", a), shards),
            _full_leaf(holder, Leaf("f", "standard", b), shards))).sum())
        for a, b in [(0, 1), (1, 2), (2, 3)]
    ]
    assert got.tolist() == singles


def test_delta_disabled_by_config(holder):
    plant(holder)
    engine = ShardedQueryEngine(
        holder, config=EngineConfig(delta_max_fraction=0.0))
    shards = list(range(4))
    call = parse("Row(f=0)").calls[0]
    engine.count("i", call, shards)
    holder.index("i").field("f").set_bit(0, 1)
    engine.count("i", call, shards)
    assert engine.counters["leaf_delta_hits"] == 0
    assert engine.counters["leaf_misses"] >= 2


def test_delta_threshold_falls_back_to_full(holder):
    """A write burst past delta_max_fraction must regather, and still be
    correct."""
    fld = plant(holder)
    engine = ShardedQueryEngine(
        holder, config=EngineConfig(delta_max_fraction=1e-9))
    shards = list(range(4))
    call = parse("Row(f=0)").calls[0]
    c0 = engine.count("i", call, shards)
    new_cols = [7, 71, 717]
    added = sum(fld.set_bit(0, c) for c in new_cols)
    assert engine.count("i", call, shards) == c0 + added
    assert engine.counters["leaf_delta_hits"] == 0


def test_property_random_writes_delta_equals_full(holder):
    """Property: across randomized write sequences — point sets/clears,
    BSI writes, bulk imports, journal overflow — the delta-maintained leaf
    and stack tensors stay byte-identical to a fresh engine's full
    regather."""
    fld = plant(holder, n_shards=3, n_rows=4)
    # Tiny journals so the sequence crosses the overflow fallback too.
    for s in range(3):
        holder.fragment("i", "f", "standard", s).delta_journal_ops = 64
    engine = ShardedQueryEngine(holder)
    shards = tuple(range(3))
    leaves = [Leaf("f", "standard", r) for r in range(4)]
    rng = np.random.default_rng(42)

    def mutate_once():
        kind = rng.integers(0, 4)
        row = int(rng.integers(0, 4))
        col = int(rng.integers(0, 3 * SHARD_WIDTH))
        if kind == 0:
            fld.set_bit(row, col)
        elif kind == 1:
            fld.clear_bit(row, col)
        elif kind == 2:  # small burst into one word neighborhood
            base = col - col % 64
            for k in range(int(rng.integers(1, 8))):
                fld.set_bit(row, min(base + k, 3 * SHARD_WIDTH - 1))
        else:  # bulk import: poisons the journal for the touched rows
            n = 200
            cols = rng.integers(0, 3 * SHARD_WIDTH, n).astype(np.uint64)
            fld.import_bits(np.full(n, row, np.uint64), cols)

    for round_ in range(8):
        mutate_once()
        # Delta-maintained tensors...
        stack = np.asarray(
            engine._stacked_leaf_tensor("i", leaves, shards, pad_pow2=True))
        plane = np.asarray(engine._gather_leaf("i", leaves[0], shards))
        # ...must equal a cold rebuild straight from storage.
        for u, leaf in enumerate(leaves):
            np.testing.assert_array_equal(
                stack[u, :3], _full_leaf(holder, leaf, list(shards)),
                err_msg=f"round {round_} leaf {u} stack diverged")
        np.testing.assert_array_equal(
            plane[:3], _full_leaf(holder, leaves[0], list(shards)),
            err_msg=f"round {round_} leaf plane diverged")
    # The sequence must actually have exercised the delta path.
    assert engine.counters["stack_delta_hits"] > 0


def test_recreated_index_never_serves_stale_delta(holder):
    """A deleted+recreated index resets generation counters while the
    engine's name-keyed caches survive; the incarnation half of the
    fingerprint must force a full regather even when the fresh counter
    climbs back past the cached generation."""
    fld = plant(holder, n_shards=2, n_rows=2)
    engine = ShardedQueryEngine(holder)
    shards = list(range(2))
    call = parse("Row(f=0)").calls[0]
    old = engine.count("i", call, shards)
    gen0 = holder.fragment("i", "f", "standard", 0).generation
    assert old > 0

    holder.delete_index("i")
    idx = holder.create_index("i")
    fld = idx.create_field("f")
    # Different, smaller content; push the fresh generation past the
    # cached one with journaled single-bit writes.
    for k in range(gen0 + 3):
        fld.set_bit(0, k)
    got = engine.count("i", call, shards)
    assert got == gen0 + 3, (got, gen0)
    assert engine.counters["leaf_delta_hits"] == 0  # full regather, no delta


def test_recreated_index_never_serves_stale_memo(holder):
    """Memo epoch fast path: a recreated index's fresh epoch climbing back
    to a stored entry's value must not alias the old count."""
    plant(holder, n_shards=1, n_rows=1)
    engine = ShardedQueryEngine(holder)
    call = parse("Row(f=0)").calls[0]
    old = engine.count("i", call, [0])
    epoch0 = holder.index("i").write_epoch.value
    holder.delete_index("i")
    fld = holder.create_index("i").create_field("f")
    for k in range(epoch0):  # drive the fresh epoch to the stored value
        fld.set_bit(0, k)
    assert holder.index("i").write_epoch.value == epoch0
    got = engine.count("i", call, [0])
    assert got == epoch0 != old


def test_recreated_field_never_serves_stale_memo(holder):
    """delete_field must bump the index write epoch: the recreated field
    shares the index's WriteEpoch instance, so without the bump the memo's
    O(1) fast path would keep serving the deleted field's counts."""
    plant(holder, n_shards=1, n_rows=1)
    engine = ShardedQueryEngine(holder)
    call = parse("Row(f=0)").calls[0]
    old = engine.count("i", call, [0])
    assert old > 0
    idx = holder.index("i")
    idx.delete_field("f")
    idx.create_field("f")  # empty
    assert engine.count("i", call, [0]) == 0


def test_stack_delta_keeps_pad_rows_in_sync(holder):
    """pow2 pad rows duplicate leaf 0; a delta touching leaf 0 must update
    them too, preserving the full-rebuild invariant (pad == leaf 0's
    current plane)."""
    fld = plant(holder, n_shards=2, n_rows=3)
    engine = ShardedQueryEngine(holder)
    shards = (0, 1)
    leaves = [Leaf("f", "standard", r) for r in range(3)]  # pads to 4
    engine._stacked_leaf_tensor("i", leaves, shards, pad_pow2=True)
    fld.set_bit(0, 12345)
    stack = np.asarray(
        engine._stacked_leaf_tensor("i", leaves, shards, pad_pow2=True))
    assert engine.counters["stack_delta_hits"] > 0
    assert stack.shape[0] == 4
    np.testing.assert_array_equal(stack[3], stack[0])
    np.testing.assert_array_equal(
        stack[0, :2], _full_leaf(holder, leaves[0], list(shards)))


# ----------------------------------------------- byte-cache accounting


class TestByteCacheAccounting:
    """The delta path republishes entries in place, so the byte counters
    must be provably exact across insert/replace/evict first."""

    def _engine(self, holder):
        return ShardedQueryEngine(holder)

    def _sum(self, cache):
        return sum(e[1].nbytes for e in cache.values())

    def test_insert_replace_evict_accounting(self, holder):
        plant(holder, n_shards=1, n_rows=1, per_row=4)
        engine = self._engine(holder)
        cache, used, budget = {}, 0, 100
        a = np.zeros(10, np.uint8)  # 10 bytes
        b = np.zeros(40, np.uint8)
        c = np.zeros(60, np.uint8)
        with engine._lock:
            used = engine._byte_cache_put(cache, "a", ((), a), budget, used,
                                          "leaf_evictions")
            used = engine._byte_cache_put(cache, "b", ((), b), budget, used,
                                          "leaf_evictions")
        assert used == self._sum(cache) == 50
        # Replace key "a" with a bigger payload: no double count.
        with engine._lock:
            used = engine._byte_cache_put(cache, "a", ((), b), budget, used,
                                          "leaf_evictions")
        assert used == self._sum(cache) == 80
        assert engine.counters["leaf_evictions"] == 0
        # Pushing past budget evicts LRU ("b" was least recently put).
        with engine._lock:
            used = engine._byte_cache_put(cache, "c", ((), c), budget, used,
                                          "leaf_evictions")
        assert used == self._sum(cache)
        assert used <= budget
        assert "c" in cache
        assert engine.counters["leaf_evictions"] > 0

    def test_oversized_entry_keeps_itself(self, holder):
        plant(holder, n_shards=1, n_rows=1, per_row=4)
        engine = self._engine(holder)
        cache, used = {}, 0
        big = np.zeros(500, np.uint8)
        with engine._lock:
            used = engine._byte_cache_put(cache, "k", ((), big), 100, used,
                                          "leaf_evictions")
        # An over-budget entry still resides (evicting it would thrash);
        # accounting stays exact.
        assert list(cache) == ["k"]
        assert used == self._sum(cache) == 500

    def test_live_refresh_accounting_through_delta(self, holder):
        """End to end: deltas and full refreshes across writes keep
        leaf/stack byte counters equal to the resident sum."""
        fld = plant(holder)
        engine = ShardedQueryEngine(holder)
        shards = tuple(range(4))
        leaves = [Leaf("f", "standard", r) for r in range(2)]
        for k in range(6):
            engine._stacked_leaf_tensor("i", leaves, shards, pad_pow2=True)
            engine._gather_leaf("i", leaves[0], shards)
            fld.set_bit(k % 2, k * 64)
        with engine._lock:
            assert engine._leaf_bytes == sum(
                e[1].nbytes for e in engine._leaf_cache.values())
            assert engine._stack_bytes == sum(
                e[1].nbytes for e in engine._stack_cache.values())


# ------------------------------------------------- memo epoch fast path


def test_memo_probe_short_circuits_on_quiet_epoch(holder, monkeypatch):
    plant(holder)
    idx = holder.index("i")
    idx.create_field_if_not_exists("g")
    idx.field("g").set_bit(1, 2)
    engine = ShardedQueryEngine(holder)
    shards = list(range(4))
    call = parse("Intersect(Row(f=0), Row(f=1))").calls[0]
    want = engine.count("i", call, shards)

    walks = {"n": 0}
    real_fp = engine._fingerprint

    def counting_fp(*a, **kw):
        walks["n"] += 1
        return real_fp(*a, **kw)

    monkeypatch.setattr(engine, "_fingerprint", counting_fp)
    # Quiet index: the repeat probe must answer WITHOUT the O(U x S)
    # fingerprint walk.
    assert engine.count("i", call, shards) == want
    assert walks["n"] == 0
    # A write to an unrelated field bumps the epoch: one walk re-validates
    # (fp unchanged -> still a hit), and the refreshed epoch makes the
    # next probe O(1) again.
    idx.field("g").set_bit(1, 77)
    assert engine.count("i", call, shards) == want
    assert walks["n"] > 0
    walks["n"] = 0
    assert engine.count("i", call, shards) == want
    assert walks["n"] == 0
    # A write to a member fragment invalidates for real.
    idx.field("f").set_bit(0, 13)
    got = engine.count("i", call, shards)
    frag0 = holder.fragment("i", "f", "standard", 0)
    assert got == want + (1 if frag0.bit(1, 13) else 0)
