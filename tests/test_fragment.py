"""Fragment tests (model: /root/reference/fragment_internal_test.go).

Covers setBit/clearBit, BSI value/sum/min/max/range, TopN (cache sizes,
src-intersection, tanimoto), merkle blocks, WAL + snapshot durability across
reopen, bulk import, and cache persistence.
"""

import numpy as np
import pytest

from pilosa_tpu.constants import CACHE_TYPE_RANKED, SHARD_WIDTH
from pilosa_tpu.core.fragment import Fragment, TopOptions
from pilosa_tpu.core.row import Row


def make_fragment(tmp_path=None, shard=0, **kw):
    path = str(tmp_path / f"frag.{shard}") if tmp_path else None
    f = Fragment(path, "i", "f", "standard", shard, **kw)
    f.open()
    return f


def test_set_clear_bit(tmp_path):
    f = make_fragment(tmp_path)
    assert f.set_bit(120, 1)
    assert f.set_bit(120, 6)
    assert f.set_bit(121, 0)
    assert not f.set_bit(120, 6)  # already set
    assert list(f.row(120).columns()) == [1, 6]
    assert f.row_count(120) == 2
    assert f.clear_bit(120, 1)
    assert not f.clear_bit(120, 1)
    assert list(f.row(120).columns()) == [6]


def test_shard_offset_columns(tmp_path):
    f = make_fragment(tmp_path, shard=2)
    base = 2 * SHARD_WIDTH
    assert f.set_bit(7, base + 5)
    assert list(f.row(7).columns()) == [base + 5]
    with pytest.raises(Exception):
        f.set_bit(7, 5)  # column outside shard


def test_wal_and_snapshot_durability(tmp_path):
    f = make_fragment(tmp_path, max_op_n=5)
    for i in range(12):  # crosses snapshot threshold twice
        f.set_bit(1, i)
    f.close()
    f2 = make_fragment(tmp_path)
    assert list(f2.row(1).columns()) == list(range(12))


def test_wal_replay_without_snapshot(tmp_path):
    f = make_fragment(tmp_path, max_op_n=10_000)
    f.set_bit(3, 42)
    f.clear_bit(3, 42)
    f.set_bit(3, 43)
    f.close()
    f2 = make_fragment(tmp_path)
    assert list(f2.row(3).columns()) == [43]


def test_bsi_value_roundtrip(tmp_path):
    f = make_fragment(tmp_path)
    assert f.set_value(100, 8, 177)
    value, exists = f.value(100, 8)
    assert (value, exists) == (177, True)
    _, exists = f.value(101, 8)
    assert not exists
    # Overwrite.
    f.set_value(100, 8, 23)
    assert f.value(100, 8) == (23, True)


def test_bsi_sum_min_max(tmp_path):
    f = make_fragment(tmp_path)
    vals = {10: 7, 20: 100, 30: 100, 40: 3}
    for col, v in vals.items():
        f.set_value(col, 8, v)
    assert f.sum(None, 8) == (210, 4)
    assert f.min(None, 8) == (3, 1)
    assert f.max(None, 8) == (100, 2)
    filt = Row(columns=[10, 20])
    assert f.sum(filt, 8) == (107, 2)
    assert f.min(filt, 8) == (7, 1)
    assert f.max(filt, 8) == (100, 1)


def test_bsi_range(tmp_path):
    f = make_fragment(tmp_path)
    vals = {1: 10, 2: 20, 3: 30, 4: 40}
    for col, v in vals.items():
        f.set_value(col, 8, v)
    assert list(f.range_op("eq", 8, 20).columns()) == [2]
    assert list(f.range_op("neq", 8, 20).columns()) == [1, 3, 4]
    assert list(f.range_op("lt", 8, 30).columns()) == [1, 2]
    assert list(f.range_op("lte", 8, 30).columns()) == [1, 2, 3]
    assert list(f.range_op("gt", 8, 20).columns()) == [3, 4]
    assert list(f.range_op("gte", 8, 20).columns()) == [2, 3, 4]
    assert list(f.range_between(8, 15, 35).columns()) == [2, 3]
    assert list(f.not_null(8).columns()) == [1, 2, 3, 4]


def test_top_basic(tmp_path):
    f = make_fragment(tmp_path)
    for col in range(5):
        f.set_bit(100, col)
    for col in range(3):
        f.set_bit(101, col)
    f.set_bit(102, 0)
    pairs = f.top(TopOptions(n=2))
    assert [(p.id, p.count) for p in pairs] == [(100, 5), (101, 3)]
    # All rows when n=0.
    pairs = f.top(TopOptions(n=0))
    assert [(p.id, p.count) for p in pairs] == [(100, 5), (101, 3), (102, 1)]


def test_top_with_src(tmp_path):
    f = make_fragment(tmp_path)
    for col in range(10):
        f.set_bit(100, col)
    for col in range(4, 12):
        f.set_bit(101, col)
    for col in range(8, 9):
        f.set_bit(102, col)
    src = Row(columns=list(range(5, 20)))
    pairs = f.top(TopOptions(n=2, src=src))
    # row 101 ∩ src = {5..11} = 7; row 100 ∩ src = {5..9} = 5; row 102 = 1
    assert [(p.id, p.count) for p in pairs] == [(101, 7), (100, 5)]


def test_top_row_ids(tmp_path):
    f = make_fragment(tmp_path)
    for col in range(5):
        f.set_bit(100, col)
    for col in range(3):
        f.set_bit(101, col)
    f.set_bit(102, 9)
    pairs = f.top(TopOptions(n=1, row_ids=[101, 102]))
    # Explicit row ids disable truncation (reference fragment.go:873-876).
    assert [(p.id, p.count) for p in pairs] == [(101, 3), (102, 1)]


def test_top_min_threshold(tmp_path):
    f = make_fragment(tmp_path)
    for col in range(5):
        f.set_bit(100, col)
    for col in range(3):
        f.set_bit(101, col)
    f.set_bit(102, 0)
    pairs = f.top(TopOptions(n=10, min_threshold=3))
    assert [(p.id, p.count) for p in pairs] == [(100, 5), (101, 3)]


def test_top_tanimoto(tmp_path):
    f = make_fragment(tmp_path)
    # src = {0..9}; row 100 = {0..9} (tanimoto 100), row 101 = {0..4,20..24}
    # (intersection 5, union 15 → ceil(5*100/15)=34), row 102 = {50} (0).
    for col in range(10):
        f.set_bit(100, col)
    for col in list(range(5)) + list(range(20, 25)):
        f.set_bit(101, col)
    f.set_bit(102, 50)
    src = Row(columns=list(range(10)))
    pairs = f.top(TopOptions(src=src, tanimoto_threshold=50))
    assert [(p.id, p.count) for p in pairs] == [(100, 10)]
    pairs = f.top(TopOptions(src=src, tanimoto_threshold=30))
    assert [(p.id, p.count) for p in pairs] == [(100, 10), (101, 5)]


def test_top_attr_filter(tmp_path):
    class AttrStore:
        def attrs(self, row_id):
            return {"x": row_id % 2}

    f = make_fragment(tmp_path, row_attr_store=AttrStore())
    for col in range(5):
        f.set_bit(100, col)
    for col in range(3):
        f.set_bit(101, col)
    pairs = f.top(TopOptions(n=10, filter_name="x", filter_values=[1]))
    assert [(p.id, p.count) for p in pairs] == [(101, 3)]


def test_blocks_change_on_write(tmp_path):
    f = make_fragment(tmp_path)
    f.set_bit(0, 1)
    b1 = f.blocks()
    assert [b.id for b in b1] == [0]
    f.set_bit(0, 2)
    b2 = f.blocks()
    assert b1[0].checksum != b2[0].checksum
    f.set_bit(250, 1)  # block 2
    assert [b.id for b in f.blocks()] == [0, 2]


def test_merge_block_consensus(tmp_path):
    f = make_fragment(tmp_path)
    f.set_bit(0, 1)  # local has (0,1)
    # Two replicas both have (0,2) and neither has (0,1): consensus = {(0,2)}.
    replica = (np.array([0]), np.array([2]))
    sets, clears = f.merge_block(0, [replica, replica])
    assert list(f.row(0).columns()) == [2]
    assert sets == [[], []] and clears == [[], []]


def test_open_is_lazy_mmap_with_copy_on_write(tmp_path):
    """Reopen parses container payloads zero-copy from an mmap (open cost
    O(headers), no double-buffering; fragment.go:167-224 mmaps likewise).
    Dense payloads stay read-only views until first mutation promotes them."""
    import mmap as mmap_mod

    f = make_fragment(tmp_path)
    for col in range(0, 12000, 2):  # dense, non-runny: serializes as bitset
        f.set_bit(3, col)
    f.set_bit(4, 9)  # sparse container (array form)
    f.snapshot()
    f.close()

    from pilosa_tpu.constants import SHARD_WIDTH

    f2 = make_fragment(tmp_path)
    dense = f2.storage.containers[(3 * SHARD_WIDTH) >> 16]
    assert dense.bits is not None and not dense.bits.flags.writeable
    assert isinstance(dense.bits.base, (mmap_mod.mmap, memoryview)) or isinstance(
        getattr(dense.bits.base, "obj", None), mmap_mod.mmap
    )
    assert f2.row_count(3) == 6000 and f2.bit(4, 9)
    # Copy-on-write: mutating the dense row must not touch the file.
    before = open(f2.path, "rb").read()
    assert f2.set_bit(3, 6001)
    assert dense.bits.flags.writeable  # promoted to a private copy
    assert f2.row_count(3) == 6001
    # Snapshot replaces the inode; stale views stay valid and reopen agrees.
    f2.snapshot()
    f2.close()
    f3 = make_fragment(tmp_path)
    assert f3.row_count(3) == 6001 and f3.bit(4, 9)
    f3.close()


def test_merge_block_rejects_out_of_range_replica_data(tmp_path):
    """Replica pairs outside the block must not wrap uint64 into phantom
    positions that reach consensus (block 0 spans rows 0..99): they are
    dropped before voting."""
    from pilosa_tpu.constants import HASH_BLOCK_SIZE

    f = make_fragment(tmp_path)
    f.set_bit(0, 1)
    # Both replicas agree on (0,1) but also send garbage: a row beyond the
    # block and, for block_id>0 semantics, a row below it (wraps negative).
    bad = (np.array([0, HASH_BLOCK_SIZE + 5], dtype=np.uint64),
           np.array([1, 7], dtype=np.uint64))
    sets, clears = f.merge_block(0, [bad, bad])
    assert list(f.row(0).columns()) == [1]
    assert f.row(HASH_BLOCK_SIZE + 5).count() == 0  # no phantom row
    assert sets == [[], []] and clears == [[], []]
    # Below-block garbage for a non-zero block wraps uint64; also dropped.
    f.set_bit(HASH_BLOCK_SIZE * 2, 3)  # block 2
    bad2 = (np.array([HASH_BLOCK_SIZE * 2, 1], dtype=np.uint64),
            np.array([3, 9], dtype=np.uint64))
    sets, clears = f.merge_block(2, [bad2, bad2])
    assert list(f.row(HASH_BLOCK_SIZE * 2).columns()) == [3]
    assert f.row(1).count() == 0
    assert sets == [[], []] and clears == [[], []]


def test_bulk_import(tmp_path):
    f = make_fragment(tmp_path)
    rows = np.array([1, 1, 2, 2, 2])
    cols = np.array([10, 20, 10, 30, 40])
    f.bulk_import(rows, cols)
    assert list(f.row(1).columns()) == [10, 20]
    assert list(f.row(2).columns()) == [10, 30, 40]
    pairs = f.top(TopOptions(n=2))
    assert [(p.id, p.count) for p in pairs] == [(2, 3), (1, 2)]


def test_import_value(tmp_path):
    f = make_fragment(tmp_path)
    cols = np.array([5, 6, 7])
    vals = np.array([100, 0, 255])
    f.import_value(cols, vals, 8)
    assert f.value(5, 8) == (100, True)
    assert f.value(6, 8) == (0, True)
    assert f.value(7, 8) == (255, True)
    assert f.sum(None, 8) == (355, 3)


def test_cache_persistence(tmp_path):
    f = make_fragment(tmp_path, cache_type=CACHE_TYPE_RANKED)
    for col in range(5):
        f.set_bit(7, col)
    f.close()
    f2 = make_fragment(tmp_path)
    assert f2.cache.get(7) == 5


def test_write_read_roundtrip(tmp_path):
    f = make_fragment(tmp_path)
    f.set_bit(1, 10)
    f.set_bit(2, 20)
    import io

    buf = io.BytesIO()
    f.write_to(buf)
    buf.seek(0)
    g = make_fragment(tmp_path / "other" if False else None)
    g = Fragment(None, "i", "f", "standard", 0)
    g.open()
    g.read_from(buf)
    assert list(g.row(1).columns()) == [10]
    assert list(g.row(2).columns()) == [20]
    assert g.cache.get(1) == 1


def test_merge_block_dense_scale(tmp_path):
    """Anti-entropy consensus over a dense block (>1M bits) must run at
    numpy speed, not per-pair Python objects (fragment.go:1176-1293)."""
    import time

    from pilosa_tpu.constants import SHARD_WIDTH

    f = make_fragment(tmp_path)
    # Local replica: rows 0-1 dense (even columns), plus noise missing
    # from the others.
    local = np.arange(0, SHARD_WIDTH, 2, dtype=np.uint64)
    f.bulk_import(np.zeros(len(local), dtype=np.uint64), local)
    f.bulk_import(np.ones(len(local), dtype=np.uint64), local)
    # Replica A: same + extra bits; replica B: same as A. 2-of-3 majority
    # should adopt the extras locally.
    extra = np.arange(1, 200_001, 2, dtype=np.uint64)  # odd cols, row 0
    rows_a = np.concatenate([np.zeros(len(local) + len(extra), dtype=np.uint64),
                             np.ones(len(local), dtype=np.uint64)])
    cols_a = np.concatenate([local, extra, local])
    t0 = time.monotonic()
    sets, clears = f.merge_block(0, [(rows_a, cols_a), (rows_a.copy(), cols_a.copy())])
    dt = time.monotonic() - t0
    assert dt < 10.0, f"dense merge too slow: {dt:.1f}s"
    # Local fragment adopted the majority extras.
    assert f.row_count(0) == len(local) + len(extra)
    assert f.row_count(1) == len(local)
    # Replicas already agree with consensus: no diffs pushed back.
    assert sets == [[], []] and clears == [[], []]


def test_merge_block_pushes_diffs_to_minority_replica(tmp_path):
    from pilosa_tpu.constants import SHARD_WIDTH

    f = make_fragment(tmp_path)
    f.set_bit(0, 1)
    f.set_bit(0, 2)
    # Replica agrees on bit 1 and has a spurious bit 5; majority of 2
    # ((2+1)//2 = 1 vote needed) keeps everything -> local adopts 5,
    # replica is told to set 2.
    sets, clears = f.merge_block(0, [(np.array([0, 0], dtype=np.uint64),
                                      np.array([1, 5], dtype=np.uint64))])
    assert f.bit(0, 5)
    assert (0, 2) in sets[0]
    assert clears[0] == []


def test_blocks_streaming_digest_parity(tmp_path):
    """blocks() streams containers instead of materializing slice() (8
    bytes per set bit — on run-heavy fragments that would undo the run
    form's memory bound every anti-entropy sweep). Digests must be
    byte-identical to the all-at-once oracle (_block_hash over the full
    position list), including across a run-heavy row and block gaps."""
    import numpy as np

    from pilosa_tpu.constants import HASH_BLOCK_SIZE, SHARD_WIDTH
    from pilosa_tpu.core.fragment import Fragment, _block_hash

    f = Fragment(None, "i", "f", "standard", 0)
    f.open()
    rng = np.random.default_rng(1)
    rows = rng.integers(0, 300, 20000).astype(np.uint64)
    cols = rng.integers(0, SHARD_WIDTH, 20000).astype(np.uint64)
    f.bulk_import(rows, cols)
    # A run-heavy row (runified in memory) and a far block (gap coverage).
    f.bulk_import(np.full(70000, 150, dtype=np.uint64),
                  np.arange(70000, dtype=np.uint64))
    f.bulk_import(np.array([950], dtype=np.uint64),
                  np.array([123], dtype=np.uint64))
    f.invalidate_checksums()
    got = {b.id: b.checksum for b in f.blocks()}

    vals = f.storage.slice()
    bw = HASH_BLOCK_SIZE * SHARD_WIDTH
    bids = (vals // np.uint64(bw)).astype(np.int64)
    want = {int(b): _block_hash(vals[bids == b]) for b in np.unique(bids)}
    assert got == want and len(got) >= 3


def test_concurrent_writes_lose_nothing(tmp_path):
    """Concurrent set_bit from many threads into the SAME container must
    not lose updates (reference fragment.go guards writes with f.mu; the
    container mutation is a multi-step numpy read-modify-write)."""
    import threading

    f = make_fragment(tmp_path)
    n_threads, per_thread = 8, 400
    errs = []

    def worker(t):
        try:
            for i in range(per_thread):
                f.set_bit(1, t * per_thread + i)  # all in one container
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert f.row_count(1) == n_threads * per_thread
    # WAL/snapshot survived the concurrency: reopen and recount.
    f.close()
    f2 = make_fragment(tmp_path)
    assert f2.row_count(1) == n_threads * per_thread
    f2.close()
