"""Binary node-to-node wire codec tests (reference ships protobuf
QueryResponses between nodes, internal/private.proto; this framework ships
packed bitplanes)."""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.core.row import Row
from pilosa_tpu.executor import ValCount
from pilosa_tpu.server import wire


def test_roundtrip_mixed_results():
    dense_cols = np.arange(0, SHARD_WIDTH, 2, dtype=np.uint64)
    sparse_cols = np.array([5, 99, SHARD_WIDTH + 7], dtype=np.uint64)
    row = Row(columns=np.concatenate([dense_cols, sparse_cols]))
    row.attrs = {"x": 1}
    results = [
        row,
        ValCount(val=42, count=7),
        [Pair(id=1, count=10), Pair(id=2, count=5, key="k")],
        True,
        12345,
        None,
    ]
    data = wire.encode_results(results)
    assert wire.is_wire(data)
    out = wire.decode_results(data)
    assert np.array_equal(out[0].columns(), row.columns())
    assert out[0].attrs == {"x": 1}
    assert out[1].val == 42 and out[1].count == 7
    assert [(p.id, p.count, p.key) for p in out[2]] == [(1, 10, ""), (2, 5, "k")]
    assert out[3] is True
    assert out[4] == 12345
    assert out[5] is None


def test_dense_row_is_compact():
    """A dense 1M-column row must ship as a plane (~128KiB), not a column
    list (~8MB binary / ~10MB JSON)."""
    import json

    from pilosa_tpu.server.handler import serialize_remote

    row = Row(columns=np.arange(0, SHARD_WIDTH, dtype=np.uint64))
    data = wire.encode_results([row])
    assert len(data) < 150_000
    json_len = len(json.dumps(serialize_remote(row)))
    assert len(data) * 10 < json_len


def test_sparse_row_is_column_form():
    row = Row(columns=np.array([3, 10_000], dtype=np.uint64))
    data = wire.encode_results([row])
    assert len(data) < 1000
    out = wire.decode_results(data)
    assert out[0].columns().tolist() == [3, 10_000]


def test_decode_rejects_garbage():
    with pytest.raises(ValueError):
        wire.decode_results(b"{\"results\": []}")
