"""Binary node-to-node wire codec tests (reference ships protobuf
QueryResponses between nodes, internal/private.proto; this framework ships
packed bitplanes)."""

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.core.row import Row
from pilosa_tpu.executor import ValCount
from pilosa_tpu.server import wire


def test_roundtrip_mixed_results():
    dense_cols = np.arange(0, SHARD_WIDTH, 2, dtype=np.uint64)
    sparse_cols = np.array([5, 99, SHARD_WIDTH + 7], dtype=np.uint64)
    row = Row(columns=np.concatenate([dense_cols, sparse_cols]))
    row.attrs = {"x": 1}
    results = [
        row,
        ValCount(val=42, count=7),
        [Pair(id=1, count=10), Pair(id=2, count=5, key="k")],
        True,
        12345,
        None,
    ]
    data = wire.encode_results(results)
    assert wire.is_wire(data)
    out = wire.decode_results(data)
    assert np.array_equal(out[0].columns(), row.columns())
    assert out[0].attrs == {"x": 1}
    assert out[1].val == 42 and out[1].count == 7
    assert [(p.id, p.count, p.key) for p in out[2]] == [(1, 10, ""), (2, 5, "k")]
    assert out[3] is True
    assert out[4] == 12345
    assert out[5] is None


def test_dense_row_is_compact():
    """A dense 1M-column row must ship as a plane (~128KiB), not a column
    list (~8MB binary / ~10MB JSON)."""
    import json

    from pilosa_tpu.server.handler import serialize_remote

    row = Row(columns=np.arange(0, SHARD_WIDTH, dtype=np.uint64))
    data = wire.encode_results([row])
    assert len(data) < 150_000
    json_len = len(json.dumps(serialize_remote(row)))
    assert len(data) * 10 < json_len


def test_sparse_row_is_column_form():
    row = Row(columns=np.array([3, 10_000], dtype=np.uint64))
    data = wire.encode_results([row])
    assert len(data) < 1000
    out = wire.decode_results(data)
    assert out[0].columns().tolist() == [3, 10_000]


def test_decode_rejects_garbage():
    with pytest.raises(ValueError):
        wire.decode_results(b"{\"results\": []}")


def test_corrupt_blob_span_rejected():
    """Corrupt segment offsets must raise, not wrap (negative) or
    silently truncate (past-the-end) into a plausible-looking Row."""
    import json
    import struct

    body = wire.encode_results([Row(columns=[1, 5, 9])])
    (head_len,) = struct.unpack_from("<I", body, 4)
    header = json.loads(body[8 : 8 + head_len])
    for bad_off, bad_len in ((-8, 8), (1 << 30, 8), (0, 1 << 30), ("x", 8)):
        h = json.loads(json.dumps(header))
        h["results"][0]["segs"][0][2] = bad_off
        h["results"][0]["segs"][0][3] = bad_len
        new_head = json.dumps(h).encode()
        forged = wire.MAGIC + struct.pack("<I", len(new_head)) + new_head \
            + body[8 + head_len:]
        with pytest.raises(ValueError, match="bad blob span|bad plane"):
            wire.decode_results(forged)
