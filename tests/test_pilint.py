"""pilint self-test: every rule proven on fixture snippets (violating and
clean twins), the annotation grammar, then the real tree — tier-1 asserts
`python -m tools.pilint pilosa_tpu/` stays at zero violations, which is
what makes the PR-review invariants machine-enforced instead of
re-derived by eye each round. See docs/static-analysis.md."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.pilint.rules import RepoEnv, build_env  # noqa: E402
from tools.pilint.runner import lint_source, lint_paths  # noqa: E402


def lint(src: str, path: str = "pilosa_tpu/example.py", env: RepoEnv = None,
         rules=None):
    return lint_source(path, textwrap.dedent(src), env or RepoEnv(),
                       rules=rules)


def codes(violations):
    return [v.rule for v in violations]


# ---------------------------------------------------------------- R1


class TestSwallowedExceptions:
    def test_bare_pass_is_violation(self):
        vs = lint("""
            try:
                work()
            except Exception:
                pass
        """, rules=["R1"])
        assert codes(vs) == ["R1"]

    def test_bare_except_is_violation(self):
        vs = lint("""
            try:
                work()
            except:
                pass
        """, rules=["R1"])
        assert codes(vs) == ["R1"]

    def test_narrow_type_is_fine(self):
        vs = lint("""
            try:
                work()
            except KeyError:
                pass
        """, rules=["R1"])
        assert vs == []

    def test_reraise_is_fine(self):
        vs = lint("""
            try:
                work()
            except Exception:
                cleanup()
                raise
        """, rules=["R1"])
        assert vs == []

    def test_log_is_fine(self):
        vs = lint("""
            try:
                work()
            except Exception as e:
                logger.error("failed: %s", e)
        """, rules=["R1"])
        assert vs == []

    def test_counter_increment_is_fine(self):
        vs = lint("""
            try:
                work()
            except Exception:
                counters["errors"] += 1
        """, rules=["R1"])
        assert vs == []

    def test_stats_count_is_fine(self):
        vs = lint("""
            try:
                work()
            except Exception:
                stats.count("WorkError", 1)
        """, rules=["R1"])
        assert vs == []

    def test_captured_error_is_fine(self):
        # collect-and-raise-later (client.py parallel fan-out pattern)
        vs = lint("""
            try:
                work()
            except Exception as e:
                first_error = first_error or e
        """, rules=["R1"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            try:
                work()
            except Exception:  # pilint: allow-swallow(probe failure means fallback)
                pass
        """)
        assert vs == []

    def test_import_guard_must_catch_importerror(self):
        vs = lint("""
            try:
                import fancy_dep
            except Exception:
                fancy_dep = None
        """, rules=["R1"])
        assert codes(vs) == ["R1"]
        assert "ImportError" in vs[0].message

    def test_import_guard_annotation_does_not_suppress(self):
        vs = lint("""
            try:
                import fancy_dep
            except Exception:  # pilint: allow-swallow(optional dependency)
                fancy_dep = None
        """, rules=["R1"])
        assert codes(vs) == ["R1"]

    def test_importerror_guard_is_fine(self):
        vs = lint("""
            try:
                import fancy_dep
            except ImportError:
                fancy_dep = None
        """, rules=["R1"])
        assert vs == []


# ---------------------------------------------------------------- R2


class TestJaxFreeZones:
    def test_module_level_jax_in_zone(self):
        vs = lint("import jax\n", path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2"]

    def test_from_jax_in_zone(self):
        vs = lint("from jax import numpy\n",
                  path="pilosa_tpu/sched/batcher.py", rules=["R2"])
        assert codes(vs) == ["R2"]

    def test_jax_submodule_in_zone(self):
        vs = lint("import jax.numpy as jnp\n",
                  path="pilosa_tpu/tier/__init__.py", rules=["R2"])
        assert codes(vs) == ["R2"]

    def test_function_local_import_is_fine(self):
        vs = lint("""
            def gather():
                import jax
                return jax
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert vs == []

    def test_type_checking_guard_is_fine(self):
        vs = lint("""
            from typing import TYPE_CHECKING
            if TYPE_CHECKING:
                import jax
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert vs == []

    def test_type_checking_else_branch_still_checked(self):
        # Only the if-body is typing-only; the else branch runs at import
        # time and must still be a violation in a zone.
        vs = lint("""
            from typing import TYPE_CHECKING
            if TYPE_CHECKING:
                import jax
            else:
                import jax
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2"]

    def test_try_else_and_finally_still_checked(self):
        # Every statement list of a try executes at import time — else
        # and finally included, not just body and handlers.
        vs = lint("""
            try:
                x = 1
            except ImportError:
                x = 2
            else:
                import jax
            finally:
                import jax.numpy
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2", "R2"]

    def test_loop_bodies_still_checked(self):
        vs = lint("""
            for _ in (1,):
                import jax
            while False:
                import jax
            else:
                import jax.numpy
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2", "R2", "R2"]

    def test_outside_zone_is_fine(self):
        vs = lint("import jax\n",
                  path="pilosa_tpu/parallel/engine.py", rules=["R2"])
        assert vs == []

    def test_no_annotation_escape(self):
        vs = lint(
            "import jax  # pilint: allow-swallow(this kind does not apply)\n",
            path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2"]


# ---------------------------------------------------------------- R3


class TestBlockingUnderLock:
    def test_sleep_under_lock(self):
        vs = lint("""
            def f(self):
                with self._lock:
                    time.sleep(1)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_fsync_under_mutex(self):
        vs = lint("""
            def f(self):
                with self._mu:
                    os.fsync(fd)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_device_put_under_lock(self):
        vs = lint("""
            def f(self):
                with self._lock:
                    arr = jax.device_put(x)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_sleep_outside_lock_is_fine(self):
        vs = lint("""
            def f(self):
                with self._lock:
                    x = 1
                time.sleep(1)
        """, rules=["R3"])
        assert vs == []

    def test_nested_function_not_flagged(self):
        # the closure runs later, when the lock is not necessarily held
        vs = lint("""
            def f(self):
                with self._lock:
                    def worker():
                        time.sleep(1)
                    return worker
        """, rules=["R3"])
        assert vs == []

    def test_non_lock_with_is_fine(self):
        vs = lint("""
            def f(self):
                with open("x") as fh:
                    time.sleep(1)
        """, rules=["R3"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            def f(self):
                with self._mu:
                    # pilint: allow-blocking(close boundary, sync must land under the mutex)
                    os.fsync(fd)
        """, rules=["R3"])
        assert vs == []

    def test_module_level_with_lock_still_caught(self):
        # the call-graph walk covers function bodies; module-level lock
        # regions keep the direct lexical scan
        vs = lint("""
            import time
            with _init_lock:
                time.sleep(1)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_condition_variable_counts_as_lock(self):
        vs = lint("""
            def f(self):
                with self._demote_cv:
                    time.sleep(1)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]


# ---------------------------------------------------------------- R4


def _env_with_wiring(handler_src: str) -> RepoEnv:
    return build_env({"pilosa_tpu/server/handler.py": textwrap.dedent(handler_src)})


class TestCounterHygiene:
    def test_unwired_counter_in_class_without_snapshot(self):
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["orphan_counter"] += 1
        """, rules=["R4"])
        assert codes(vs) == ["R4"]
        assert "orphan_counter" in vs[0].message

    def test_wholesale_snapshot_export_is_fine(self):
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["thing"] += 1
                def snapshot(self):
                    return dict(self.counters)
        """, rules=["R4"])
        assert vs == []

    def test_partial_snapshot_is_not_wholesale(self):
        # A snapshot() exporting a SUBSET must not grant the class R4
        # immunity — the unexported counter is still unobservable.
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["orphan_counter"] += 1
                def snapshot(self):
                    return {"hits": self.counters["hits"]}
        """, rules=["R4"])
        assert codes(vs) == ["R4"]
        assert "orphan_counter" in vs[0].message

    def test_literal_in_wiring_corpus_is_fine(self):
        env = _env_with_wiring("""
            def handle_debug_vars(self):
                return {"orphan_counter": x.orphan_counter}
        """)
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["orphan_counter"] += 1
        """, env=env, rules=["R4"])
        assert vs == []

    def test_stats_count_fine_while_wholesale_dump_exists(self):
        env = _env_with_wiring("""
            def handle_debug_vars(self):
                out = stats.snapshot()
                return out
        """)
        vs = lint("""
            def f(stats):
                stats.count("AnythingAtAll", 1)
        """, env=env, rules=["R4"])
        assert vs == []

    def test_stats_count_flagged_without_wholesale_dump(self):
        vs = lint("""
            def f(stats):
                stats.count("LostForever", 1)
        """, rules=["R4"])
        assert codes(vs) == ["R4"]

    def test_annotation_suppresses(self):
        vs = lint("""
            class Worker:
                def run(self):
                    # pilint: allow-counter(test-only counter, asserted directly)
                    self.counters["private"] += 1
        """, rules=["R4"])
        assert vs == []

    def test_nested_class_judged_by_its_own_snapshot(self):
        # A class defined inside a method must not inherit the OUTER
        # class's wholesale-snapshot immunity.
        vs = lint("""
            class Outer:
                def make(self):
                    class Inner:
                        def run(self):
                            self.counters["inner_orphan"] += 1
                    return Inner()
                def snapshot(self):
                    return dict(self.counters)
        """, rules=["R4"])
        assert codes(vs) == ["R4"]
        assert "inner_orphan" in vs[0].message

    def test_nested_class_with_own_snapshot_is_fine(self):
        # ... and a nested class exporting its own counters wholesale is
        # clean even when the enclosing class exports nothing.
        vs = lint("""
            class Outer:
                def make(self):
                    class Inner:
                        def run(self):
                            self.counters["inner_ok"] += 1
                        def snapshot(self):
                            return dict(self.counters)
                    return Inner()
        """, rules=["R4"])
        assert vs == []

    def test_outside_pilosa_tpu_not_checked(self):
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["whatever"] += 1
        """, path="tools/example.py", rules=["R4"])
        assert vs == []


# ---------------------------------------------------------------- R5


class TestMutationEpochAudit:
    def test_mutation_without_bump(self):
        vs = lint("""
            class Fragment:
                def set_bit(self, pos):
                    return self.storage.add(pos)
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert codes(vs) == ["R5"]
        assert "set_bit" in vs[0].message

    def test_direct_generation_bump_is_fine(self):
        vs = lint("""
            class Fragment:
                def set_bit(self, pos):
                    changed = self.storage.add(pos)
                    self.generation += 1
                    return changed
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert vs == []

    def test_bump_via_helper_call_walk(self):
        vs = lint("""
            class Fragment:
                def set_bit(self, pos):
                    changed = self.storage.add(pos)
                    self._invalidate(pos)
                    return changed
                def _invalidate(self, pos):
                    self.generation += 1
                    self.epoch.bump()
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert vs == []

    def test_epoch_bump_call_is_fine(self):
        vs = lint("""
            class Fragment:
                def read_from(self, f):
                    self.storage.read_from(f)
                    self.epoch.bump()
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert vs == []

    def test_outside_core_not_checked(self):
        vs = lint("""
            class Thing:
                def mutate(self):
                    self.storage.add(1)
        """, path="pilosa_tpu/tier/manager.py", rules=["R5"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            class Fragment:
                # pilint: allow-mutation(recovery replay runs before any reader exists)
                def _replay(self, data):
                    self.storage.read_from(data)
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert vs == []


# ---------------------------------------------------------------- R6


class TestFailpointHygiene:
    def _env(self, docs=("wal-append",), fires=()):
        env = RepoEnv()
        env.failpoint_docs_loaded = True
        env.failpoint_doc_names = set(docs)
        env.failpoint_fire_sites = set(fires)
        return env

    def test_undocumented_fire_site_is_violation(self):
        vs = lint("""
            from . import failpoints

            def append(self):
                failpoints.fire("wal-apend")
        """, env=self._env(), rules=["R6"])
        assert codes(vs) == ["R6"]
        assert "wal-apend" in vs[0].message

    def test_documented_fire_site_is_fine(self):
        vs = lint("""
            from . import failpoints

            def append(self):
                failpoints.fire("wal-append")
        """, env=self._env(), rules=["R6"])
        assert vs == []

    def test_targeted_fire_site_checks_base_name(self):
        # fire() passes the target as a kwarg, so the literal IS the base
        # name — a documented name with a target kwarg stays clean.
        vs = lint("""
            from . import failpoints

            def send(self, netloc):
                failpoints.fire("wal-append", target=netloc)
        """, env=self._env(), rules=["R6"])
        assert vs == []

    def test_annotation_suppresses_fire_site(self):
        vs = lint("""
            from . import failpoints

            def append(self):
                # pilint: allow-failpoint(internal-only point, not for tests)
                failpoints.fire("secret-point")
        """, env=self._env(), rules=["R6"])
        assert vs == []

    def test_docs_not_loaded_no_ops(self):
        # Fixture/snippet runs without the docs corpus must not flag.
        env = RepoEnv()
        vs = lint("""
            from . import failpoints

            def append(self):
                failpoints.fire("whatever")
        """, env=env, rules=["R6"])
        assert vs == []

    def test_outside_pilosa_tpu_not_checked(self):
        vs = lint("""
            def f():
                fire("not-a-real-point")
        """, path="bench.py", env=self._env(), rules=["R6"])
        assert vs == []

    def test_orphan_spec_in_test_is_violation(self):
        from tools.pilint.rules import (collect_spec_sites,
                                        failpoint_orphan_violations)

        env = self._env(fires={"wal-append"})
        env.failpoint_spec_sites = collect_spec_sites(
            "tests/test_x.py", textwrap.dedent("""
                import os
                os.environ["PILOSA_TPU_FAILPOINTS"] = "wal-apend=error"
            """))
        vs = failpoint_orphan_violations(env)
        assert codes(vs) == ["R6"]
        assert "wal-apend" in vs[0].message

    def test_spec_with_fire_site_is_fine(self):
        from tools.pilint.rules import (collect_spec_sites,
                                        failpoint_orphan_violations)

        env = self._env(fires={"wal-append", "client-send"})
        env.failpoint_spec_sites = collect_spec_sites(
            "tests/test_x.py", textwrap.dedent("""
                SPEC = "wal-append=1*crash;client-send@localhost:1=drop"
                failpoints.configure("client-send", "latency", arg=5)
            """))
        assert failpoint_orphan_violations(env) == []

    def test_configure_collected_and_target_stripped(self):
        from tools.pilint.rules import collect_spec_sites

        sites = collect_spec_sites(
            "tests/test_x.py", textwrap.dedent("""
                failpoints.configure("migrate-begin@host:1", "error")
            """))
        assert [n for _, _, n in sites] == ["migrate-begin"]

    def test_allow_failpoint_annotation_excludes_spec(self):
        from tools.pilint.rules import collect_spec_sites

        sites = collect_spec_sites(
            "tests/test_x.py", textwrap.dedent("""
                failpoints.configure("p", "error")  # pilint: allow-failpoint(registry grammar test)
            """))
        assert sites == []

    def test_plain_assignment_string_not_a_spec(self):
        # Ordinary key=value literals must not parse as activation specs.
        from tools.pilint.rules import collect_spec_sites

        sites = collect_spec_sites(
            "tests/test_x.py", 'H = "content-type=application/json"\n')
        assert sites == []

    def test_docs_table_parser_reads_section_rows(self):
        from tools.pilint.rules import parse_failpoint_docs

        names = parse_failpoint_docs(textwrap.dedent("""
            ## Something else

            | `not-a-point` | x |

            ## Failpoints (`pilosa_tpu/failpoints.py`)

            | failpoint | fires at |
            |---|---|
            | `wal-append` | WAL append |
            | `device-dispatch` | engine dispatch |

            ## After

            | `also-not` | y |
        """))
        assert names == {"wal-append", "device-dispatch"}

    def test_real_tree_docs_cover_every_fire_site(self):
        """Belt and braces over the zero-violations test: the shipped
        docs table and the shipped fire sites agree exactly on names."""
        from tools.pilint.rules import (collect_fire_names,
                                        parse_failpoint_docs)
        import ast, glob

        with open(os.path.join(REPO_ROOT, "docs", "durability.md")) as f:
            doc_names = parse_failpoint_docs(f.read())
        fired = set()
        for path in glob.glob(
                os.path.join(REPO_ROOT, "pilosa_tpu", "**", "*.py"),
                recursive=True):
            with open(path) as f:
                fired |= collect_fire_names(ast.parse(f.read()))
        assert fired, "no fire sites found — collection broke"
        assert fired <= doc_names, fired - doc_names


# ---------------------------------------------------------------- R7


class TestSpanHygiene:
    def _env(self, docs=("parse", "gather"), records=("parse", "gather")):
        env = RepoEnv()
        env.span_docs_loaded = True
        env.span_doc_names = set(docs)
        env.span_record_sites = set(records)
        return env

    def test_undocumented_span_site_is_violation(self):
        vs = lint("""
            from ..obs import span as obs_span

            def f():
                with obs_span("gathr"):
                    work()
        """, env=self._env(), rules=["R7"])
        assert codes(vs) == ["R7"]

    def test_documented_span_site_is_fine(self):
        vs = lint("""
            from ..obs import span as obs_span, record as obs_record

            def f():
                with obs_span("gather"):
                    work()
                obs_record("parse", 1.0)
        """, env=self._env(), rules=["R7"])
        assert vs == []

    def test_dynamic_span_name_not_checked(self):
        # remote:<peer> hops are f-strings: statically unverifiable,
        # documented for humans, never a violation.
        vs = lint("""
            def f(trace, target):
                with trace.span(f"remote:{target.id}"):
                    work()
        """, env=self._env(), rules=["R7"])
        assert vs == []

    def test_annotation_suppresses_span_site(self):
        vs = lint("""
            from ..obs import span as obs_span

            def f():
                # pilint: allow-span(internal-only stage, not operator-facing)
                with obs_span("secret.stage"):
                    work()
        """, env=self._env(), rules=["R7"])
        assert vs == []

    def test_docs_not_loaded_no_ops(self):
        env = RepoEnv()  # span_docs_loaded stays False
        vs = lint("""
            from ..obs import span as obs_span

            def f():
                with obs_span("whatever"):
                    work()
        """, env=env, rules=["R7"])
        assert vs == []

    def test_outside_pilosa_tpu_not_checked(self):
        vs = lint("""
            span("anything-goes")
        """, path="bench.py", env=self._env(), rules=["R7"])
        assert vs == []

    def test_orphan_asserted_span_is_violation(self):
        from tools.pilint.rules import (collect_span_assert_sites,
                                        span_orphan_violations)

        env = self._env(records=("parse",))
        env.span_assert_sites = collect_span_assert_sites(
            "tests/test_x.py", textwrap.dedent("""
                def test_t(trace):
                    find_span(trace, "gathr")  # pilint: allow-span(fixture negative for this self-test)

                    assert_span(trace, "gathre")
            """))
        vs = span_orphan_violations(env)
        assert codes(vs) == ["R7"]
        assert "gathre" in vs[0].message

    def test_asserted_span_with_record_site_is_fine(self):
        from tools.pilint.rules import (collect_span_assert_sites,
                                        span_orphan_violations)

        env = self._env(records=("parse", "gather"))
        env.span_assert_sites = collect_span_assert_sites(
            "tests/test_x.py", textwrap.dedent("""
                def test_t(trace):
                    assert_span(trace, "gather")
            """))
        assert span_orphan_violations(env) == []

    def test_docs_table_parser_reads_span_section(self):
        from tools.pilint.rules import parse_span_docs

        names = parse_span_docs(textwrap.dedent("""
            ## Something else

            | `not-a-span` | x |

            ## Span reference

            | span | recorded at |
            |---|---|
            | `parse` | executor |
            | `remote:<peer>` | client hop |

            ## After

            | `also-not` | y |
        """))
        assert names == {"parse", "remote:<peer>"}

    def test_real_tree_docs_cover_every_span_site(self):
        """The shipped span table and the shipped recording sites agree:
        every constant span name recorded anywhere in pilosa_tpu/ has a
        row in docs/observability.md."""
        from tools.pilint.rules import collect_span_names, parse_span_docs
        import ast, glob

        with open(os.path.join(REPO_ROOT, "docs", "observability.md")) as f:
            doc_names = parse_span_docs(f.read())
        recorded = set()
        for path in glob.glob(
                os.path.join(REPO_ROOT, "pilosa_tpu", "**", "*.py"),
                recursive=True):
            with open(path) as f:
                recorded |= collect_span_names(ast.parse(f.read()))
        assert recorded, "no span recording sites found — collection broke"
        assert recorded <= doc_names, recorded - doc_names
        # And every acceptance stage actually records somewhere.
        for name in ("parse", "sched.wait", "batch.hold", "executor.fanout",
                     "gather", "device.dispatch", "tier.promote", "reduce"):
            assert name in recorded, name


# ------------------------------------------------------- annotation grammar


class TestAnnotationGrammar:
    def test_unknown_kind_is_violation(self):
        vs = lint("x = 1  # pilint: allow-everything(just because)\n")
        assert [v.rule for v in vs] == ["A0"]

    def test_empty_reason_is_violation(self):
        vs = lint("""
            try:
                work()
            except Exception:  # pilint: allow-swallow()
                pass
        """, rules=None)
        # the annotation still suppresses R1 (one finding per problem),
        # but the missing reason is itself flagged
        assert [v.rule for v in vs] == ["A0"]

    def test_short_reason_is_violation(self):
        vs = lint("""
            try:
                work()
            except Exception:  # pilint: allow-swallow(ok)
                pass
        """)
        assert [v.rule for v in vs] == ["A0"]

    def test_unused_annotation_is_violation(self):
        vs = lint("x = 1  # pilint: allow-swallow(nothing here swallows)\n")
        assert [v.rule for v in vs] == ["A0"]
        assert "unused" in vs[0].message

    def test_unused_blocking_annotation_exempt_when_covering_a_call(self):
        # consumed by the runtime lock checker, which honors any frame of
        # a blocking stack — possible only where a call crosses the line
        vs = lint("""
            def f(self):
                # pilint: allow-blocking(runtime-only lock context)
                self._helper_that_blocks()
        """)
        assert vs == []

    def test_unused_blocking_annotation_rot_without_any_call(self):
        # v2 narrowing (the annotation-rot sweep): no call crosses the
        # covered lines, so neither the static pass nor the runtime
        # checker can ever consume it — provably stale, delete it.
        vs = lint("x = 1  # pilint: allow-blocking(refactor left me behind)\n")
        assert [v.rule for v in vs] == ["A0"]
        assert "runtime lock checker" in vs[0].message

    def test_annotation_in_docstring_is_not_an_annotation(self):
        # lockcheck.py documents the grammar in prose; a spelling inside
        # a string literal must parse as neither annotation nor rot.
        vs = lint('''
            def f():
                """Suppress with `# pilint: allow-blocking(reason)` on the line."""
                return 1
        ''')
        assert vs == []

    def test_annotation_on_line_above(self):
        vs = lint("""
            try:
                work()
            # pilint: allow-swallow(reason lives on the line above)
            except Exception:
                pass
        """)
        assert vs == []


# ------------------------------------------------------------- real tree


class TestRealTree:
    def test_pilosa_tpu_is_clean(self):
        """THE enforcement test: the shipped tree has zero unannotated
        violations. A new swallowed except / jax import in a config
        module / blocking call under a lock / orphaned counter fails
        tier-1, not a human reviewer's attention."""
        vs = lint_paths([os.path.join(REPO_ROOT, "pilosa_tpu")],
                        repo_root=REPO_ROOT)
        assert vs == [], "\n".join(str(v) for v in vs)

    def test_cli_entry_exits_zero_on_clean_tree(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.pilint", "pilosa_tpu/"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 violations" in proc.stdout

    def test_cli_entry_exits_nonzero_on_violation(self, tmp_path):
        bad = tmp_path / "pilosa_tpu"
        bad.mkdir()
        (bad / "bad.py").write_text(
            "try:\n    work()\nexcept Exception:\n    pass\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.pilint", str(bad)],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "R1" in proc.stdout

    def test_cli_list_rules(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.pilint", "--list-rules"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0
        for rule_id in ("R1", "R2", "R3", "R4", "R5"):
            assert rule_id in proc.stdout

    def test_every_annotation_carries_reason(self):
        """Acceptance criterion: every allow-* annotation in the tree has
        a human-readable reason (the A0 grammar checks run with the full
        rule set in test_pilosa_tpu_is_clean; this asserts the grammar is
        actually exercised — the tree DOES contain annotations)."""
        from tools.pilint.core import parse_annotations

        total = 0
        for root, _dirs, files in os.walk(os.path.join(REPO_ROOT, "pilosa_tpu")):
            for name in files:
                if not name.endswith(".py"):
                    continue
                full = os.path.join(root, name)
                with open(full, "r", encoding="utf-8") as f:
                    annotations, grammar_violations = parse_annotations(
                        full, f.read())
                assert grammar_violations == [], grammar_violations
                total += len(annotations)
                for a in annotations:
                    assert len(a.reason) >= 4, (full, a)
        assert total > 0, "expected the tree to carry pilint annotations"


# ----------------------------------------------- interprocedural lock flow


class TestInterproceduralLockFlow:
    """R3's v2 half: may-hold-lock propagation through resolved call
    edges (tools/pilint/graph.py), config-bounded depth."""

    def test_helper_blocking_caught_at_depth_one(self):
        vs = lint("""
            import os

            class W:
                def commit(self):
                    with self._mu:
                        self._persist()
                def _persist(self):
                    os.fsync(self._fd)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]
        assert "reached while a lock is held" in vs[0].message
        assert "_persist" in vs[0].message

    def test_module_function_helper_caught(self):
        vs = lint("""
            import os

            def persist(fd):
                os.fsync(fd)

            class W:
                def commit(self):
                    with self._mu:
                        persist(self._fd)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_caught_at_the_depth_limit(self):
        # chain: with -> h1 -> h2 -> h3 -> h4(fsync): 4 call edges = the
        # default depth limit, still caught...
        src = """
            import os

            class W:
                def commit(self):
                    with self._mu:
                        self._h1()
                def _h1(self):
                    self._h2()
                def _h2(self):
                    self._h3()
                def _h3(self):
                    self._h4()
                def _h4(self):
                    os.fsync(self._fd)
        """
        vs = lint(src, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_beyond_the_depth_limit_not_caught(self):
        # ...and one helper deeper than the configured limit is out of
        # reach (the limit is the soundness/noise dial, CLI --depth).
        src = """
            import os

            class W:
                def commit(self):
                    with self._mu:
                        self._h1()
                def _h1(self):
                    self._h2()
                def _h2(self):
                    self._h3(self)
                def _h3(self, x):
                    os.fsync(self._fd)
        """
        assert codes(lint(src, rules=["R3"])) == ["R3"]
        vs = lint_source("pilosa_tpu/example.py", textwrap.dedent(src),
                         RepoEnv(), rules=["R3"], depth=2)
        assert vs == []

    def test_recursion_cycle_terminates(self):
        vs = lint("""
            import os

            class W:
                def commit(self):
                    with self._mu:
                        self._a()
                def _a(self):
                    self._b()
                def _b(self):
                    self._a()
                    os.fsync(self._fd)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_annotation_on_the_caller_vouches_for_the_callee(self):
        # the lock-holding caller takes responsibility for the callee
        # subtree, mirroring lockcheck's any-frame suppression
        vs = lint("""
            import os

            class W:
                def commit(self):
                    with self._mu:
                        # pilint: allow-blocking(tiny checkpoint, ordered with the ack by design)
                        self._persist()
                def _persist(self):
                    os.fsync(self._fd)
        """, rules=["R3"])
        assert vs == []

    def test_annotation_on_the_deny_line_still_suppresses(self):
        vs = lint("""
            import os

            class W:
                def commit(self):
                    with self._mu:
                        self._persist()
                def _persist(self):
                    # pilint: allow-blocking(close boundary, sync must land under the mutex)
                    os.fsync(self._fd)
        """, rules=["R3"])
        assert vs == []

    def test_import_fallback_def_in_except_body_is_visible(self):
        # a def nested inside an except-handler (the import-fallback
        # idiom) must still be a call-graph node — blocking host helpers
        # live exactly there
        vs = lint("""
            import os

            try:
                from fastlib import persist
            except ImportError:
                def persist(fd):
                    os.fsync(fd)

            class W:
                def commit(self):
                    with self._mu:
                        persist(self._fd)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_module_level_region_seeds_module_function_helper(self):
        # a module-level `with _boot_lock:` reaches a helper's fsync too
        vs = lint("""
            import os

            def _warm(fd):
                os.fsync(fd)

            with _boot_lock:
                _warm(3)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]
        assert "reached while a lock is held" in vs[0].message

    def test_nested_def_in_helper_not_lock_attributed(self):
        # a worker closure defined (not called) in the helper runs later
        vs = lint("""
            import os

            class W:
                def commit(self):
                    with self._mu:
                        self._persist()
                def _persist(self):
                    def later():
                        os.fsync(self._fd)
                    return later
        """, rules=["R3"])
        assert vs == []

    def test_direct_and_helper_hits_both_reported(self):
        vs = lint("""
            import os, time

            class W:
                def commit(self):
                    with self._mu:
                        time.sleep(0.1)
                        self._persist()
                def _persist(self):
                    os.fsync(self._fd)
        """, rules=["R3"])
        assert codes(vs) == ["R3", "R3"]


# ---------------------------------------------------------------- R8


class TestGuardedMaterialization:
    ENGINE = "pilosa_tpu/parallel/engine.py"
    COLLECTIVE = "pilosa_tpu/parallel/collective.py"

    def test_forcing_guard_result_outside_guard(self):
        vs = lint("""
            import numpy as np

            class Engine:
                def count_batch(self, leaves):
                    fn = self._fn_build(self._fns, ("sig",), self._build)
                    arr = self._device_call(("sig",), lambda: fn(leaves))
                    return np.asarray(arr)[:4]
        """, path=self.ENGINE, rules=["R8"])
        assert codes(vs) == ["R8"]
        assert "asarray" in vs[0].message

    def test_forcing_inside_the_guard_thunk_is_fine(self):
        vs = lint("""
            import numpy as np

            class Engine:
                def count_batch(self, leaves):
                    fn = self._fn_build(self._fns, ("sig",), self._build)
                    return self._device_call(
                        ("sig",), lambda: np.asarray(fn(leaves))[:4])
        """, path=self.ENGINE, rules=["R8"])
        assert vs == []

    def test_block_until_ready_outside_guard(self):
        vs = lint("""
            class Engine:
                def bitmap(self, leaves):
                    fn = self._fn(("sig",), self._build)
                    planes = self._device_call(("sig",), lambda: fn(leaves))
                    return planes.block_until_ready()
        """, path=self.ENGINE, rules=["R8"])
        assert codes(vs) == ["R8"]

    def test_block_until_ready_inside_guard_is_fine(self):
        vs = lint("""
            class Engine:
                def bitmap(self, leaves):
                    fn = self._fn(("sig",), self._build)
                    return self._device_call(
                        ("sig",), lambda: fn(leaves).block_until_ready())
        """, path=self.ENGINE, rules=["R8"])
        assert vs == []

    def test_tainted_returning_helper_forced_outside_guard(self):
        # count_batch_async returns the unmaterialized array BY DESIGN;
        # a caller forcing it outside the guard is the bug
        vs = lint("""
            import numpy as np

            class Engine:
                def count_async(self, leaves):
                    fn = self._fn_build(self._fns, ("sig",), self._build)
                    return self._device_call(("sig",), lambda: fn(leaves))
                def count(self, leaves):
                    return np.asarray(self.count_async(leaves))
        """, path=self.ENGINE, rules=["R8"])
        assert codes(vs) == ["R8"]

    def test_helper_dominated_by_ladder_root_is_fine(self):
        # collective: _run_count materializes, but is reached only from
        # _enter (the runner-thread ladder) — guarded interprocedurally
        vs = lint("""
            import numpy as np

            class Backend:
                def _enter(self, desc):
                    return self._run_count(desc)
                def _run_count(self, desc):
                    fn = self._fn(("sig",), self._build)
                    lo, hi = fn(desc)
                    return np.asarray(lo), np.asarray(hi)
        """, path=self.COLLECTIVE, rules=["R8"])
        assert vs == []

    def test_same_shape_not_dominated_is_flagged(self):
        # identical body, but reachable from a public method too: the
        # materialization can execute outside the ladder
        vs = lint("""
            import numpy as np

            class Backend:
                def preview(self, desc):
                    return self._run_count(desc)
                def _run_count(self, desc):
                    fn = self._fn(("sig",), self._build)
                    lo, hi = fn(desc)
                    return np.asarray(lo), np.asarray(hi)
        """, path=self.COLLECTIVE, rules=["R8"])
        assert codes(vs) == ["R8", "R8"]

    def test_named_def_thunk_passed_to_guard_is_fine(self):
        vs = lint("""
            import numpy as np

            class Engine:
                def topn(self, rows):
                    fn = self._fn_build(self._fns, ("sig",), self._build)
                    def run():
                        return np.asarray(fn(rows))[:2]
                    return self._device_call(None, run)
        """, path=self.ENGINE, rules=["R8"])
        assert vs == []

    def test_helper_called_only_from_guard_lambda_is_dominated(self):
        # the helper's one call site lives INSIDE a guard thunk, so its
        # materialization executes under the ladder — not a finding
        vs = lint("""
            import numpy as np

            class Engine:
                def _pull(self, fn, leaves):
                    return np.asarray(fn(leaves))
                def count(self, leaves):
                    fn = self._fn(("sig",), self._build)
                    return self._device_call(
                        ("sig",), lambda: self._pull(fn, leaves))
        """, path=self.ENGINE, rules=["R8"])
        assert vs == []

    def test_host_input_asarray_untainted(self):
        vs = lint("""
            import numpy as np

            class Engine:
                def topn(self, row_ids):
                    req = np.asarray(row_ids)
                    return req
        """, path=self.ENGINE, rules=["R8"])
        assert vs == []

    def test_outside_dispatch_modules_not_checked(self):
        vs = lint("""
            import numpy as np

            class X:
                def f(self, leaves):
                    fn = self._fn(("sig",), self._build)
                    return np.asarray(fn(leaves))
        """, path="pilosa_tpu/executor.py", rules=["R8"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            import numpy as np

            class Engine:
                def count(self, leaves):
                    fn = self._fn(("sig",), self._build)
                    # pilint: allow-materialize(startup warm path, faults handled by caller)
                    return np.asarray(fn(leaves))
        """, path=self.ENGINE, rules=["R8"])
        assert vs == []


# ---------------------------------------------------------------- R9


class TestProbeClaimHygiene:
    HEALTH = "pilosa_tpu/parallel/device_health.py"

    BUG = """
        class H:
            def plan(self, sig):
                now = self.clock()
                s = self._sigs.get(sig)
                gate = self._gate_locked(self._plane, now)
                if gate is False:
                    return "host"
                if s is not None:
                    if self._gate_locked(s, now) is False:
                        return "host"
                return "device"
            def _gate_locked(self, b, now):
                b.probe_at = now
                return True
    """

    def test_claim_before_due_check_is_violation(self):
        vs = lint(self.BUG, path=self.HEALTH, rules=["R9"])
        assert codes(vs) == ["R9"]
        assert "orphans the claimed probe" in vs[0].message

    def test_due_check_before_first_claim_is_fine(self):
        vs = lint("""
            class H:
                def plan(self, sig):
                    now = self.clock()
                    s = self._sigs.get(sig)
                    if s is not None and not self._due_locked(s, now):
                        return "host"
                    gate = self._gate_locked(self._plane, now)
                    if gate is False:
                        return "host"
                    if s is not None:
                        self._gate_locked(s, now)
                    return "device"
                def _due_locked(self, b, now):
                    return now - b.probe_at >= 1.0
                def _gate_locked(self, b, now):
                    b.probe_at = now
                    return True
        """, path=self.HEALTH, rules=["R9"])
        assert vs == []

    def test_single_claim_site_is_fine(self):
        # one breaker involved: nothing to orphan by short-circuiting
        vs = lint("""
            class H:
                def allow_request(self, node_id):
                    return self._gate_locked(self._peer(node_id), 0.0)
                def _gate_locked(self, b, now):
                    b.probe_at = now
                    return True
        """, path=self.HEALTH, rules=["R9"])
        assert vs == []

    def test_outside_health_modules_not_checked(self):
        vs = lint(self.BUG, path="pilosa_tpu/executor.py", rules=["R9"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            class H:
                def plan(self, sig):
                    now = self.clock()
                    # pilint: allow-probe(single-breaker path: the second claim is unreachable with sig=None)
                    gate = self._gate_locked(self._plane, now)
                    if gate is False:
                        return "host"
                    self._gate_locked(self._sigs[sig], now)
                    return "device"
                def _gate_locked(self, b, now):
                    b.probe_at = now
                    return True
        """, path=self.HEALTH, rules=["R9"])
        assert vs == []


# ---------------------------------------------------------------- R10


class TestNoneGuardedStats:
    def test_unguarded_holder_stats_count(self):
        vs = lint("""
            class Executor:
                def f(self):
                    self.holder.stats.count("X", 1)
        """, rules=["R10"])
        assert codes(vs) == ["R10"]
        assert "self.holder.stats" in vs[0].message

    def test_if_truthy_guard_is_fine(self):
        vs = lint("""
            class Executor:
                def f(self):
                    if self.holder.stats:
                        self.holder.stats.count("X", 1)
        """, rules=["R10"])
        assert vs == []

    def test_is_not_none_guard_is_fine(self):
        vs = lint("""
            class Executor:
                def _count_stat(self, name):
                    if self.holder.stats is not None:
                        self.holder.stats.count(name, 1)
        """, rules=["R10"])
        assert vs == []

    def test_early_return_bailout_is_fine(self):
        vs = lint("""
            class Executor:
                def f(self):
                    if self.holder.stats is None:
                        return
                    self.holder.stats.count("X", 1)
        """, rules=["R10"])
        assert vs == []

    def test_and_guard_is_fine(self):
        vs = lint("""
            class T:
                def stop(self):
                    self.stats and self.stats.timing("Q", 1.0)
        """, rules=["R10"])
        assert vs == []

    def test_guard_of_a_different_chain_does_not_count(self):
        vs = lint("""
            class Executor:
                def f(self):
                    if self.other.stats:
                        self.holder.stats.count("X", 1)
        """, rules=["R10"])
        assert codes(vs) == ["R10"]

    def test_timing_checked_too(self):
        vs = lint("""
            class T:
                def stop(self):
                    self.stats.timing("Q", 1.0)
        """, rules=["R10"])
        assert codes(vs) == ["R10"]

    def test_ctor_coalesced_self_stats_is_never_none(self):
        # Server.stats = stats or InMemoryStatsClient(): that holder is
        # never stats-less, no guard needed
        vs = lint("""
            class Server:
                def __init__(self, stats=None):
                    self.stats = stats or InMemoryStatsClient()
                def tick(self):
                    self.stats.count("AntiEntropy", 1)
        """, rules=["R10"])
        assert vs == []

    def test_annotated_coalescing_assignment_also_counts(self):
        # ast.AnnAssign, not ast.Assign — the annotation must not hide
        # the coalescing from the nullability analysis
        vs = lint("""
            class Server:
                def __init__(self, stats=None):
                    self.stats: object = stats or InMemoryStatsClient()
                def tick(self):
                    self.stats.count("AntiEntropy", 1)
        """, rules=["R10"])
        assert vs == []

    def test_plain_ctor_assignment_stays_nullable(self):
        vs = lint("""
            class Fragment:
                def __init__(self, stats=None):
                    self.stats = stats
                def set_bit(self):
                    self.stats.count("setBit", 1)
        """, rules=["R10"])
        assert codes(vs) == ["R10"]

    def test_outside_pilosa_tpu_not_checked(self):
        vs = lint("""
            stats.count("X", 1)
        """, path="bench.py", rules=["R10"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            class Executor:
                def f(self):
                    # pilint: allow-stat(test-only executor, holder always carries stats here)
                    self.holder.stats.count("X", 1)
        """, rules=["R10"])
        assert vs == []


# ---------------------------------------------------------------- R11


def _r11_env(constants=(), cli=(), docs="", set_attrs=(), dump_rows=None):
    env = RepoEnv()
    env.config_surface_loaded = True
    env.config_constants = set(constants)
    env.cli_constants = set(cli)
    env.config_docs = {"docs/engine-caches.md": docs}
    env.config_set_attrs = set(set_attrs)
    env.config_dump_rows = dict(dump_rows or {})
    return env


_R11_FULL = dict(
    constants={"ENGINE_GATHER_WORKERS", "engine_gather_workers",
               "ENGINE_PLAN_CACHE", "engine_plan_cache"},
    cli={"--engine-gather-workers", "--engine-plan-cache"},
    docs="knobs: `gather-workers` and `plan-cache` do things",
    set_attrs={"self.engine.gather_workers", "self.engine.plan_cache"},
    dump_rows={"engine": {"gather-workers = ", "plan-cache = "}},
)


class TestConfigSurface:
    SRC = """
        from dataclasses import dataclass

        @dataclass
        class EngineConfig:
            gather_workers: int = 0
            plan_cache: int = 1
    """

    def test_complete_surface_is_fine(self):
        vs = lint(self.SRC, path="pilosa_tpu/parallel/__init__.py",
                  env=_r11_env(**_R11_FULL), rules=["R11"])
        assert vs == []

    def test_missing_surfaces_listed(self):
        partial = dict(_R11_FULL)
        partial["dump_rows"] = {"engine": {"gather-workers = "}}
        partial["docs"] = "only `gather-workers` here"
        vs = lint(self.SRC, path="pilosa_tpu/parallel/__init__.py",
                  env=_r11_env(**partial), rules=["R11"])
        assert codes(vs) == ["R11"]
        assert "plan_cache" in vs[0].message
        assert "to_toml" in vs[0].message
        assert "docs/engine-caches.md" in vs[0].message
        assert "gather_workers" not in vs[0].message

    def test_shared_key_in_another_section_does_not_mask_drift(self):
        # `delta-max-fraction` exists in BOTH [engine] and [collective];
        # a dump row present only under the OTHER section's header must
        # not satisfy this section's check (the masking bug class)
        masked = dict(_R11_FULL)
        masked["dump_rows"] = {"engine": {"gather-workers = "},
                               "collective": {"plan-cache = "}}
        vs = lint(self.SRC, path="pilosa_tpu/parallel/__init__.py",
                  env=_r11_env(**masked), rules=["R11"])
        assert codes(vs) == ["R11"]
        assert "plan_cache" in vs[0].message and "to_toml" in vs[0].message

    def test_parse_store_scoped_to_section(self):
        # another section parsing the same field name must not count
        unparsed = dict(_R11_FULL)
        unparsed["set_attrs"] = {"self.engine.gather_workers",
                                 "self.collective.plan_cache"}
        vs = lint(self.SRC, path="pilosa_tpu/parallel/__init__.py",
                  env=_r11_env(**unparsed), rules=["R11"])
        assert codes(vs) == ["R11"]
        assert "_apply_dict" in vs[0].message

    def test_env_not_loaded_no_ops(self):
        vs = lint(self.SRC, path="pilosa_tpu/parallel/__init__.py",
                  env=RepoEnv(), rules=["R11"])
        assert vs == []

    def test_non_section_dataclass_not_checked(self):
        vs = lint("""
            from dataclasses import dataclass

            @dataclass
            class SomethingElseConfig:
                whatever: int = 0
        """, path="pilosa_tpu/parallel/__init__.py",
                  env=_r11_env(**_R11_FULL), rules=["R11"])
        assert vs == []

    def test_underscore_field_skipped(self):
        vs = lint("""
            from dataclasses import dataclass

            @dataclass
            class EngineConfig:
                _internal: int = 0
        """, path="pilosa_tpu/parallel/__init__.py",
                  env=_r11_env(**_R11_FULL), rules=["R11"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            from dataclasses import dataclass

            @dataclass
            class EngineConfig:
                # pilint: allow-config(internal tuning knob, deliberately off the operator surface)
                secret_knob: int = 0
        """, path="pilosa_tpu/parallel/__init__.py",
                  env=_r11_env(**_R11_FULL), rules=["R11"])
        assert vs == []

    def test_real_tree_surface_is_complete(self):
        """Belt and braces over the zero-violations test: rebuild the
        R11 corpus from the shipped config.py/cli.py/docs and assert
        every section dataclass field reaches every surface."""
        vs = lint_paths([os.path.join(REPO_ROOT, "pilosa_tpu")],
                        repo_root=REPO_ROOT, rules=["R11"])
        assert vs == [], "\\n".join(str(v) for v in vs)


# ------------------------------------------------- reverted-fix corpus


CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "fixtures", "pilint_corpus")

# fixture stem -> (pretend repo path, rule). The pretend path routes the
# fixture into the right rule scope (R8 judges the dispatch modules, R9
# the health modules, ...).
CORPUS = {
    "r3_helper_blocking": ("pilosa_tpu/tier/manager.py", "R3"),
    "r8_unguarded_materialization": ("pilosa_tpu/parallel/engine.py", "R8"),
    "r9_device_probe": ("pilosa_tpu/parallel/device_health.py", "R9"),
    "r9_collective_probe": ("pilosa_tpu/parallel/device_health.py", "R9"),
    "r10_unguarded_stat": ("pilosa_tpu/executor.py", "R10"),
    "r11_config_drift": ("pilosa_tpu/parallel/__init__.py", "R11"),
}

_R11_DRIFT_FULL = dict(
    constants={"ENGINE_GATHER_WORKERS", "engine_gather_workers",
               "ENGINE_PLAN_CACHE", "engine_plan_cache"},
    cli={"--engine-gather-workers", "--engine-plan-cache"},
    set_attrs={"self.engine.gather_workers", "self.engine.plan_cache"},
)


class TestRevertedFixCorpus:
    """THE acceptance corpus: every PR 8/9/12 review-round bug, reverted
    back into a fixture, is flagged by exactly its rule — and every
    clean twin (the shape the fix shipped) passes. A rule regression
    that would let one of these shapes back into review fails here."""

    def _lint_fixture(self, stem, suffix, rule):
        path, _ = CORPUS[stem]
        full = os.path.join(CORPUS_DIR, f"{stem}_{suffix}.py")
        with open(full, "r", encoding="utf-8") as f:
            src = f.read()
        if rule == "R11":
            # the drift fixture reconstructs plan-cache missing from the
            # dump + doc; the clean twin gets the full surface corpus
            docs = ("`gather-workers` `plan-cache`" if suffix == "clean"
                    else "`gather-workers` only")
            rows = {"engine": {"gather-workers = ", "plan-cache = "}}
            if suffix == "bug":
                rows = {"engine": {"gather-workers = "}}
            env = _r11_env(constants=_R11_DRIFT_FULL["constants"],
                           cli=_R11_DRIFT_FULL["cli"], docs=docs,
                           set_attrs=_R11_DRIFT_FULL["set_attrs"],
                           dump_rows=rows)
        else:
            env = RepoEnv()
        return lint_source(path, src, env, rules=[rule])

    @pytest.mark.parametrize("stem", sorted(CORPUS))
    def test_bug_fixture_is_flagged(self, stem):
        _, rule = CORPUS[stem]
        vs = self._lint_fixture(stem, "bug", rule)
        assert vs, f"{stem}_bug.py: expected {rule} findings, got none"
        assert {v.rule for v in vs} == {rule}, vs

    @pytest.mark.parametrize("stem", sorted(CORPUS))
    def test_clean_twin_passes(self, stem):
        _, rule = CORPUS[stem]
        vs = self._lint_fixture(stem, "clean", rule)
        assert vs == [], "\\n".join(str(v) for v in vs)

    def test_corpus_is_complete(self):
        # >= 6 reconstructed review-round bugs, each with a clean twin
        assert len(CORPUS) >= 6
        for stem in CORPUS:
            for suffix in ("bug", "clean"):
                assert os.path.exists(
                    os.path.join(CORPUS_DIR, f"{stem}_{suffix}.py")), (
                    stem, suffix)


# ------------------------------------------------------- incremental mode


class TestChangedMode:
    def test_changed_lints_only_diffed_files(self, tmp_path):
        import subprocess as sp

        repo = tmp_path / "repo"
        (repo / "pilosa_tpu").mkdir(parents=True)
        (repo / "pilosa_tpu" / "clean.py").write_text("x = 1\n")
        env = dict(os.environ,
                   GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
        for args in (["git", "init", "-q"], ["git", "add", "."],
                     ["git", "commit", "-qm", "seed"]):
            sp.run(args, cwd=repo, env=env, check=True, capture_output=True)
        # a tracked file grows a violation; an untracked bad file appears
        (repo / "pilosa_tpu" / "clean.py").write_text(
            "try:\n    work()\nexcept Exception:\n    pass\n")
        (repo / "pilosa_tpu" / "fresh.py").write_text(
            "try:\n    work()\nexcept Exception:\n    pass\n")
        proc = sp.run(
            [sys.executable, "-m", "tools.pilint", "--changed", "HEAD",
             "--root", str(repo)],
            cwd=repo, env=dict(env, PYTHONPATH=REPO_ROOT),
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "clean.py" in proc.stdout and "fresh.py" in proc.stdout
        assert proc.stdout.count("R1") == 2

    def test_changed_with_no_changes_exits_zero(self, tmp_path):
        import subprocess as sp

        repo = tmp_path / "repo"
        (repo / "pilosa_tpu").mkdir(parents=True)
        (repo / "pilosa_tpu" / "clean.py").write_text("x = 1\n")
        env = dict(os.environ,
                   GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
        for args in (["git", "init", "-q"], ["git", "add", "."],
                     ["git", "commit", "-qm", "seed"]):
            sp.run(args, cwd=repo, env=env, check=True, capture_output=True)
        proc = sp.run(
            [sys.executable, "-m", "tools.pilint", "--changed", "HEAD",
             "--root", str(repo)],
            cwd=repo, env=dict(env, PYTHONPATH=REPO_ROOT),
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 violations" in proc.stdout

    def test_depth_flag_parsed(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.pilint", "--depth", "0",
             "pilosa_tpu/errors.py"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 2  # depth must be >= 1
