"""pilint self-test: every rule proven on fixture snippets (violating and
clean twins), the annotation grammar, then the real tree — tier-1 asserts
`python -m tools.pilint pilosa_tpu/` stays at zero violations, which is
what makes the PR-review invariants machine-enforced instead of
re-derived by eye each round. See docs/static-analysis.md."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.pilint.rules import RepoEnv, build_env  # noqa: E402
from tools.pilint.runner import lint_source, lint_paths  # noqa: E402


def lint(src: str, path: str = "pilosa_tpu/example.py", env: RepoEnv = None,
         rules=None):
    return lint_source(path, textwrap.dedent(src), env or RepoEnv(),
                       rules=rules)


def codes(violations):
    return [v.rule for v in violations]


# ---------------------------------------------------------------- R1


class TestSwallowedExceptions:
    def test_bare_pass_is_violation(self):
        vs = lint("""
            try:
                work()
            except Exception:
                pass
        """, rules=["R1"])
        assert codes(vs) == ["R1"]

    def test_bare_except_is_violation(self):
        vs = lint("""
            try:
                work()
            except:
                pass
        """, rules=["R1"])
        assert codes(vs) == ["R1"]

    def test_narrow_type_is_fine(self):
        vs = lint("""
            try:
                work()
            except KeyError:
                pass
        """, rules=["R1"])
        assert vs == []

    def test_reraise_is_fine(self):
        vs = lint("""
            try:
                work()
            except Exception:
                cleanup()
                raise
        """, rules=["R1"])
        assert vs == []

    def test_log_is_fine(self):
        vs = lint("""
            try:
                work()
            except Exception as e:
                logger.error("failed: %s", e)
        """, rules=["R1"])
        assert vs == []

    def test_counter_increment_is_fine(self):
        vs = lint("""
            try:
                work()
            except Exception:
                counters["errors"] += 1
        """, rules=["R1"])
        assert vs == []

    def test_stats_count_is_fine(self):
        vs = lint("""
            try:
                work()
            except Exception:
                stats.count("WorkError", 1)
        """, rules=["R1"])
        assert vs == []

    def test_captured_error_is_fine(self):
        # collect-and-raise-later (client.py parallel fan-out pattern)
        vs = lint("""
            try:
                work()
            except Exception as e:
                first_error = first_error or e
        """, rules=["R1"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            try:
                work()
            except Exception:  # pilint: allow-swallow(probe failure means fallback)
                pass
        """)
        assert vs == []

    def test_import_guard_must_catch_importerror(self):
        vs = lint("""
            try:
                import fancy_dep
            except Exception:
                fancy_dep = None
        """, rules=["R1"])
        assert codes(vs) == ["R1"]
        assert "ImportError" in vs[0].message

    def test_import_guard_annotation_does_not_suppress(self):
        vs = lint("""
            try:
                import fancy_dep
            except Exception:  # pilint: allow-swallow(optional dependency)
                fancy_dep = None
        """, rules=["R1"])
        assert codes(vs) == ["R1"]

    def test_importerror_guard_is_fine(self):
        vs = lint("""
            try:
                import fancy_dep
            except ImportError:
                fancy_dep = None
        """, rules=["R1"])
        assert vs == []


# ---------------------------------------------------------------- R2


class TestJaxFreeZones:
    def test_module_level_jax_in_zone(self):
        vs = lint("import jax\n", path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2"]

    def test_from_jax_in_zone(self):
        vs = lint("from jax import numpy\n",
                  path="pilosa_tpu/sched/batcher.py", rules=["R2"])
        assert codes(vs) == ["R2"]

    def test_jax_submodule_in_zone(self):
        vs = lint("import jax.numpy as jnp\n",
                  path="pilosa_tpu/tier/__init__.py", rules=["R2"])
        assert codes(vs) == ["R2"]

    def test_function_local_import_is_fine(self):
        vs = lint("""
            def gather():
                import jax
                return jax
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert vs == []

    def test_type_checking_guard_is_fine(self):
        vs = lint("""
            from typing import TYPE_CHECKING
            if TYPE_CHECKING:
                import jax
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert vs == []

    def test_type_checking_else_branch_still_checked(self):
        # Only the if-body is typing-only; the else branch runs at import
        # time and must still be a violation in a zone.
        vs = lint("""
            from typing import TYPE_CHECKING
            if TYPE_CHECKING:
                import jax
            else:
                import jax
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2"]

    def test_try_else_and_finally_still_checked(self):
        # Every statement list of a try executes at import time — else
        # and finally included, not just body and handlers.
        vs = lint("""
            try:
                x = 1
            except ImportError:
                x = 2
            else:
                import jax
            finally:
                import jax.numpy
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2", "R2"]

    def test_loop_bodies_still_checked(self):
        vs = lint("""
            for _ in (1,):
                import jax
            while False:
                import jax
            else:
                import jax.numpy
        """, path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2", "R2", "R2"]

    def test_outside_zone_is_fine(self):
        vs = lint("import jax\n",
                  path="pilosa_tpu/parallel/engine.py", rules=["R2"])
        assert vs == []

    def test_no_annotation_escape(self):
        vs = lint(
            "import jax  # pilint: allow-swallow(this kind does not apply)\n",
            path="pilosa_tpu/config.py", rules=["R2"])
        assert codes(vs) == ["R2"]


# ---------------------------------------------------------------- R3


class TestBlockingUnderLock:
    def test_sleep_under_lock(self):
        vs = lint("""
            def f(self):
                with self._lock:
                    time.sleep(1)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_fsync_under_mutex(self):
        vs = lint("""
            def f(self):
                with self._mu:
                    os.fsync(fd)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_device_put_under_lock(self):
        vs = lint("""
            def f(self):
                with self._lock:
                    arr = jax.device_put(x)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]

    def test_sleep_outside_lock_is_fine(self):
        vs = lint("""
            def f(self):
                with self._lock:
                    x = 1
                time.sleep(1)
        """, rules=["R3"])
        assert vs == []

    def test_nested_function_not_flagged(self):
        # the closure runs later, when the lock is not necessarily held
        vs = lint("""
            def f(self):
                with self._lock:
                    def worker():
                        time.sleep(1)
                    return worker
        """, rules=["R3"])
        assert vs == []

    def test_non_lock_with_is_fine(self):
        vs = lint("""
            def f(self):
                with open("x") as fh:
                    time.sleep(1)
        """, rules=["R3"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            def f(self):
                with self._mu:
                    # pilint: allow-blocking(close boundary, sync must land under the mutex)
                    os.fsync(fd)
        """, rules=["R3"])
        assert vs == []

    def test_condition_variable_counts_as_lock(self):
        vs = lint("""
            def f(self):
                with self._demote_cv:
                    time.sleep(1)
        """, rules=["R3"])
        assert codes(vs) == ["R3"]


# ---------------------------------------------------------------- R4


def _env_with_wiring(handler_src: str) -> RepoEnv:
    return build_env({"pilosa_tpu/server/handler.py": textwrap.dedent(handler_src)})


class TestCounterHygiene:
    def test_unwired_counter_in_class_without_snapshot(self):
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["orphan_counter"] += 1
        """, rules=["R4"])
        assert codes(vs) == ["R4"]
        assert "orphan_counter" in vs[0].message

    def test_wholesale_snapshot_export_is_fine(self):
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["thing"] += 1
                def snapshot(self):
                    return dict(self.counters)
        """, rules=["R4"])
        assert vs == []

    def test_partial_snapshot_is_not_wholesale(self):
        # A snapshot() exporting a SUBSET must not grant the class R4
        # immunity — the unexported counter is still unobservable.
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["orphan_counter"] += 1
                def snapshot(self):
                    return {"hits": self.counters["hits"]}
        """, rules=["R4"])
        assert codes(vs) == ["R4"]
        assert "orphan_counter" in vs[0].message

    def test_literal_in_wiring_corpus_is_fine(self):
        env = _env_with_wiring("""
            def handle_debug_vars(self):
                return {"orphan_counter": x.orphan_counter}
        """)
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["orphan_counter"] += 1
        """, env=env, rules=["R4"])
        assert vs == []

    def test_stats_count_fine_while_wholesale_dump_exists(self):
        env = _env_with_wiring("""
            def handle_debug_vars(self):
                out = stats.snapshot()
                return out
        """)
        vs = lint("""
            def f(stats):
                stats.count("AnythingAtAll", 1)
        """, env=env, rules=["R4"])
        assert vs == []

    def test_stats_count_flagged_without_wholesale_dump(self):
        vs = lint("""
            def f(stats):
                stats.count("LostForever", 1)
        """, rules=["R4"])
        assert codes(vs) == ["R4"]

    def test_annotation_suppresses(self):
        vs = lint("""
            class Worker:
                def run(self):
                    # pilint: allow-counter(test-only counter, asserted directly)
                    self.counters["private"] += 1
        """, rules=["R4"])
        assert vs == []

    def test_nested_class_judged_by_its_own_snapshot(self):
        # A class defined inside a method must not inherit the OUTER
        # class's wholesale-snapshot immunity.
        vs = lint("""
            class Outer:
                def make(self):
                    class Inner:
                        def run(self):
                            self.counters["inner_orphan"] += 1
                    return Inner()
                def snapshot(self):
                    return dict(self.counters)
        """, rules=["R4"])
        assert codes(vs) == ["R4"]
        assert "inner_orphan" in vs[0].message

    def test_nested_class_with_own_snapshot_is_fine(self):
        # ... and a nested class exporting its own counters wholesale is
        # clean even when the enclosing class exports nothing.
        vs = lint("""
            class Outer:
                def make(self):
                    class Inner:
                        def run(self):
                            self.counters["inner_ok"] += 1
                        def snapshot(self):
                            return dict(self.counters)
                    return Inner()
        """, rules=["R4"])
        assert vs == []

    def test_outside_pilosa_tpu_not_checked(self):
        vs = lint("""
            class Worker:
                def run(self):
                    self.counters["whatever"] += 1
        """, path="tools/example.py", rules=["R4"])
        assert vs == []


# ---------------------------------------------------------------- R5


class TestMutationEpochAudit:
    def test_mutation_without_bump(self):
        vs = lint("""
            class Fragment:
                def set_bit(self, pos):
                    return self.storage.add(pos)
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert codes(vs) == ["R5"]
        assert "set_bit" in vs[0].message

    def test_direct_generation_bump_is_fine(self):
        vs = lint("""
            class Fragment:
                def set_bit(self, pos):
                    changed = self.storage.add(pos)
                    self.generation += 1
                    return changed
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert vs == []

    def test_bump_via_helper_call_walk(self):
        vs = lint("""
            class Fragment:
                def set_bit(self, pos):
                    changed = self.storage.add(pos)
                    self._invalidate(pos)
                    return changed
                def _invalidate(self, pos):
                    self.generation += 1
                    self.epoch.bump()
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert vs == []

    def test_epoch_bump_call_is_fine(self):
        vs = lint("""
            class Fragment:
                def read_from(self, f):
                    self.storage.read_from(f)
                    self.epoch.bump()
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert vs == []

    def test_outside_core_not_checked(self):
        vs = lint("""
            class Thing:
                def mutate(self):
                    self.storage.add(1)
        """, path="pilosa_tpu/tier/manager.py", rules=["R5"])
        assert vs == []

    def test_annotation_suppresses(self):
        vs = lint("""
            class Fragment:
                # pilint: allow-mutation(recovery replay runs before any reader exists)
                def _replay(self, data):
                    self.storage.read_from(data)
        """, path="pilosa_tpu/core/fragment.py", rules=["R5"])
        assert vs == []


# ---------------------------------------------------------------- R6


class TestFailpointHygiene:
    def _env(self, docs=("wal-append",), fires=()):
        env = RepoEnv()
        env.failpoint_docs_loaded = True
        env.failpoint_doc_names = set(docs)
        env.failpoint_fire_sites = set(fires)
        return env

    def test_undocumented_fire_site_is_violation(self):
        vs = lint("""
            from . import failpoints

            def append(self):
                failpoints.fire("wal-apend")
        """, env=self._env(), rules=["R6"])
        assert codes(vs) == ["R6"]
        assert "wal-apend" in vs[0].message

    def test_documented_fire_site_is_fine(self):
        vs = lint("""
            from . import failpoints

            def append(self):
                failpoints.fire("wal-append")
        """, env=self._env(), rules=["R6"])
        assert vs == []

    def test_targeted_fire_site_checks_base_name(self):
        # fire() passes the target as a kwarg, so the literal IS the base
        # name — a documented name with a target kwarg stays clean.
        vs = lint("""
            from . import failpoints

            def send(self, netloc):
                failpoints.fire("wal-append", target=netloc)
        """, env=self._env(), rules=["R6"])
        assert vs == []

    def test_annotation_suppresses_fire_site(self):
        vs = lint("""
            from . import failpoints

            def append(self):
                # pilint: allow-failpoint(internal-only point, not for tests)
                failpoints.fire("secret-point")
        """, env=self._env(), rules=["R6"])
        assert vs == []

    def test_docs_not_loaded_no_ops(self):
        # Fixture/snippet runs without the docs corpus must not flag.
        env = RepoEnv()
        vs = lint("""
            from . import failpoints

            def append(self):
                failpoints.fire("whatever")
        """, env=env, rules=["R6"])
        assert vs == []

    def test_outside_pilosa_tpu_not_checked(self):
        vs = lint("""
            def f():
                fire("not-a-real-point")
        """, path="bench.py", env=self._env(), rules=["R6"])
        assert vs == []

    def test_orphan_spec_in_test_is_violation(self):
        from tools.pilint.rules import (collect_spec_sites,
                                        failpoint_orphan_violations)

        env = self._env(fires={"wal-append"})
        env.failpoint_spec_sites = collect_spec_sites(
            "tests/test_x.py", textwrap.dedent("""
                import os
                os.environ["PILOSA_TPU_FAILPOINTS"] = "wal-apend=error"
            """))
        vs = failpoint_orphan_violations(env)
        assert codes(vs) == ["R6"]
        assert "wal-apend" in vs[0].message

    def test_spec_with_fire_site_is_fine(self):
        from tools.pilint.rules import (collect_spec_sites,
                                        failpoint_orphan_violations)

        env = self._env(fires={"wal-append", "client-send"})
        env.failpoint_spec_sites = collect_spec_sites(
            "tests/test_x.py", textwrap.dedent("""
                SPEC = "wal-append=1*crash;client-send@localhost:1=drop"
                failpoints.configure("client-send", "latency", arg=5)
            """))
        assert failpoint_orphan_violations(env) == []

    def test_configure_collected_and_target_stripped(self):
        from tools.pilint.rules import collect_spec_sites

        sites = collect_spec_sites(
            "tests/test_x.py", textwrap.dedent("""
                failpoints.configure("migrate-begin@host:1", "error")
            """))
        assert [n for _, _, n in sites] == ["migrate-begin"]

    def test_allow_failpoint_annotation_excludes_spec(self):
        from tools.pilint.rules import collect_spec_sites

        sites = collect_spec_sites(
            "tests/test_x.py", textwrap.dedent("""
                failpoints.configure("p", "error")  # pilint: allow-failpoint(registry grammar test)
            """))
        assert sites == []

    def test_plain_assignment_string_not_a_spec(self):
        # Ordinary key=value literals must not parse as activation specs.
        from tools.pilint.rules import collect_spec_sites

        sites = collect_spec_sites(
            "tests/test_x.py", 'H = "content-type=application/json"\n')
        assert sites == []

    def test_docs_table_parser_reads_section_rows(self):
        from tools.pilint.rules import parse_failpoint_docs

        names = parse_failpoint_docs(textwrap.dedent("""
            ## Something else

            | `not-a-point` | x |

            ## Failpoints (`pilosa_tpu/failpoints.py`)

            | failpoint | fires at |
            |---|---|
            | `wal-append` | WAL append |
            | `device-dispatch` | engine dispatch |

            ## After

            | `also-not` | y |
        """))
        assert names == {"wal-append", "device-dispatch"}

    def test_real_tree_docs_cover_every_fire_site(self):
        """Belt and braces over the zero-violations test: the shipped
        docs table and the shipped fire sites agree exactly on names."""
        from tools.pilint.rules import (collect_fire_names,
                                        parse_failpoint_docs)
        import ast, glob

        with open(os.path.join(REPO_ROOT, "docs", "durability.md")) as f:
            doc_names = parse_failpoint_docs(f.read())
        fired = set()
        for path in glob.glob(
                os.path.join(REPO_ROOT, "pilosa_tpu", "**", "*.py"),
                recursive=True):
            with open(path) as f:
                fired |= collect_fire_names(ast.parse(f.read()))
        assert fired, "no fire sites found — collection broke"
        assert fired <= doc_names, fired - doc_names


# ---------------------------------------------------------------- R7


class TestSpanHygiene:
    def _env(self, docs=("parse", "gather"), records=("parse", "gather")):
        env = RepoEnv()
        env.span_docs_loaded = True
        env.span_doc_names = set(docs)
        env.span_record_sites = set(records)
        return env

    def test_undocumented_span_site_is_violation(self):
        vs = lint("""
            from ..obs import span as obs_span

            def f():
                with obs_span("gathr"):
                    work()
        """, env=self._env(), rules=["R7"])
        assert codes(vs) == ["R7"]

    def test_documented_span_site_is_fine(self):
        vs = lint("""
            from ..obs import span as obs_span, record as obs_record

            def f():
                with obs_span("gather"):
                    work()
                obs_record("parse", 1.0)
        """, env=self._env(), rules=["R7"])
        assert vs == []

    def test_dynamic_span_name_not_checked(self):
        # remote:<peer> hops are f-strings: statically unverifiable,
        # documented for humans, never a violation.
        vs = lint("""
            def f(trace, target):
                with trace.span(f"remote:{target.id}"):
                    work()
        """, env=self._env(), rules=["R7"])
        assert vs == []

    def test_annotation_suppresses_span_site(self):
        vs = lint("""
            from ..obs import span as obs_span

            def f():
                # pilint: allow-span(internal-only stage, not operator-facing)
                with obs_span("secret.stage"):
                    work()
        """, env=self._env(), rules=["R7"])
        assert vs == []

    def test_docs_not_loaded_no_ops(self):
        env = RepoEnv()  # span_docs_loaded stays False
        vs = lint("""
            from ..obs import span as obs_span

            def f():
                with obs_span("whatever"):
                    work()
        """, env=env, rules=["R7"])
        assert vs == []

    def test_outside_pilosa_tpu_not_checked(self):
        vs = lint("""
            span("anything-goes")
        """, path="bench.py", env=self._env(), rules=["R7"])
        assert vs == []

    def test_orphan_asserted_span_is_violation(self):
        from tools.pilint.rules import (collect_span_assert_sites,
                                        span_orphan_violations)

        env = self._env(records=("parse",))
        env.span_assert_sites = collect_span_assert_sites(
            "tests/test_x.py", textwrap.dedent("""
                def test_t(trace):
                    find_span(trace, "gathr")  # pilint: allow-span(fixture negative for this self-test)

                    assert_span(trace, "gathre")
            """))
        vs = span_orphan_violations(env)
        assert codes(vs) == ["R7"]
        assert "gathre" in vs[0].message

    def test_asserted_span_with_record_site_is_fine(self):
        from tools.pilint.rules import (collect_span_assert_sites,
                                        span_orphan_violations)

        env = self._env(records=("parse", "gather"))
        env.span_assert_sites = collect_span_assert_sites(
            "tests/test_x.py", textwrap.dedent("""
                def test_t(trace):
                    assert_span(trace, "gather")
            """))
        assert span_orphan_violations(env) == []

    def test_docs_table_parser_reads_span_section(self):
        from tools.pilint.rules import parse_span_docs

        names = parse_span_docs(textwrap.dedent("""
            ## Something else

            | `not-a-span` | x |

            ## Span reference

            | span | recorded at |
            |---|---|
            | `parse` | executor |
            | `remote:<peer>` | client hop |

            ## After

            | `also-not` | y |
        """))
        assert names == {"parse", "remote:<peer>"}

    def test_real_tree_docs_cover_every_span_site(self):
        """The shipped span table and the shipped recording sites agree:
        every constant span name recorded anywhere in pilosa_tpu/ has a
        row in docs/observability.md."""
        from tools.pilint.rules import collect_span_names, parse_span_docs
        import ast, glob

        with open(os.path.join(REPO_ROOT, "docs", "observability.md")) as f:
            doc_names = parse_span_docs(f.read())
        recorded = set()
        for path in glob.glob(
                os.path.join(REPO_ROOT, "pilosa_tpu", "**", "*.py"),
                recursive=True):
            with open(path) as f:
                recorded |= collect_span_names(ast.parse(f.read()))
        assert recorded, "no span recording sites found — collection broke"
        assert recorded <= doc_names, recorded - doc_names
        # And every acceptance stage actually records somewhere.
        for name in ("parse", "sched.wait", "batch.hold", "executor.fanout",
                     "gather", "device.dispatch", "tier.promote", "reduce"):
            assert name in recorded, name


# ------------------------------------------------------- annotation grammar


class TestAnnotationGrammar:
    def test_unknown_kind_is_violation(self):
        vs = lint("x = 1  # pilint: allow-everything(just because)\n")
        assert [v.rule for v in vs] == ["A0"]

    def test_empty_reason_is_violation(self):
        vs = lint("""
            try:
                work()
            except Exception:  # pilint: allow-swallow()
                pass
        """, rules=None)
        # the annotation still suppresses R1 (one finding per problem),
        # but the missing reason is itself flagged
        assert [v.rule for v in vs] == ["A0"]

    def test_short_reason_is_violation(self):
        vs = lint("""
            try:
                work()
            except Exception:  # pilint: allow-swallow(ok)
                pass
        """)
        assert [v.rule for v in vs] == ["A0"]

    def test_unused_annotation_is_violation(self):
        vs = lint("x = 1  # pilint: allow-swallow(nothing here swallows)\n")
        assert [v.rule for v in vs] == ["A0"]
        assert "unused" in vs[0].message

    def test_unused_blocking_annotation_exempt(self):
        # consumed by the runtime lock checker, which this pass can't see
        vs = lint("x = 1  # pilint: allow-blocking(runtime-only lock context)\n")
        assert vs == []

    def test_annotation_on_line_above(self):
        vs = lint("""
            try:
                work()
            # pilint: allow-swallow(reason lives on the line above)
            except Exception:
                pass
        """)
        assert vs == []


# ------------------------------------------------------------- real tree


class TestRealTree:
    def test_pilosa_tpu_is_clean(self):
        """THE enforcement test: the shipped tree has zero unannotated
        violations. A new swallowed except / jax import in a config
        module / blocking call under a lock / orphaned counter fails
        tier-1, not a human reviewer's attention."""
        vs = lint_paths([os.path.join(REPO_ROOT, "pilosa_tpu")],
                        repo_root=REPO_ROOT)
        assert vs == [], "\n".join(str(v) for v in vs)

    def test_cli_entry_exits_zero_on_clean_tree(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.pilint", "pilosa_tpu/"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 violations" in proc.stdout

    def test_cli_entry_exits_nonzero_on_violation(self, tmp_path):
        bad = tmp_path / "pilosa_tpu"
        bad.mkdir()
        (bad / "bad.py").write_text(
            "try:\n    work()\nexcept Exception:\n    pass\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.pilint", str(bad)],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "R1" in proc.stdout

    def test_cli_list_rules(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.pilint", "--list-rules"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0
        for rule_id in ("R1", "R2", "R3", "R4", "R5"):
            assert rule_id in proc.stdout

    def test_every_annotation_carries_reason(self):
        """Acceptance criterion: every allow-* annotation in the tree has
        a human-readable reason (the A0 grammar checks run with the full
        rule set in test_pilosa_tpu_is_clean; this asserts the grammar is
        actually exercised — the tree DOES contain annotations)."""
        from tools.pilint.core import parse_annotations

        total = 0
        for root, _dirs, files in os.walk(os.path.join(REPO_ROOT, "pilosa_tpu")):
            for name in files:
                if not name.endswith(".py"):
                    continue
                full = os.path.join(root, name)
                with open(full, "r", encoding="utf-8") as f:
                    annotations, grammar_violations = parse_annotations(
                        full, f.read())
                assert grammar_violations == [], grammar_violations
                total += len(annotations)
                for a in annotations:
                    assert len(a.reason) >= 4, (full, a)
        assert total > 0, "expected the tree to carry pilint annotations"
