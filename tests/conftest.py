"""Force tests onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is unavailable in CI; shardings are validated on an
8-device CPU mesh (the driver separately dry-run-compiles multi-chip via
__graft_entry__.dryrun_multichip).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
