"""Force tests onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is unavailable in CI; shardings are validated on an
8-device CPU mesh (the driver separately dry-run-compiles multi-chip via
__graft_entry__.dryrun_multichip). jax is pre-imported by the environment,
so platform selection must go through jax.config (env vars are too late) —
this works as long as no backend has been initialized yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older jax: XLA_FLAGS above covers it


import subprocess
import sys
import threading
import time

import pytest

# Lock-order / blocking-under-lock instrumentation (devtools/lockcheck.py):
# opt-in via PILOSA_TPU_LOCKCHECK=1, installed HERE — before any test
# imports pilosa_tpu — so module-level locks (failpoints._mu, native._lock)
# and every instance lock are constructed through the instrumented
# factories. Loaded by FILE PATH, not `from pilosa_tpu.devtools import
# lockcheck`: the package import would execute pilosa_tpu/__init__ first,
# constructing those module-level locks as raw _thread locks before
# install() patches the factories. lockcheck.py is stdlib-only so a path
# load is safe; seeding sys.modules makes later package imports reuse this
# instance (one global checker state). tests/test_lockcheck.py drives an
# instrumented subprocess run of the chaos/tier/rebalance tests through
# this hook and asserts the report (written at sessionfinish, path in
# PILOSA_TPU_LOCKCHECK_OUT) comes back empty.
_LOCKCHECK = os.environ.get("PILOSA_TPU_LOCKCHECK") == "1"
if _LOCKCHECK:
    import importlib.util

    _lc_spec = importlib.util.spec_from_file_location(
        "pilosa_tpu.devtools.lockcheck",
        os.path.join(os.path.dirname(__file__), "..",
                     "pilosa_tpu", "devtools", "lockcheck.py"))
    _lockcheck = importlib.util.module_from_spec(_lc_spec)
    sys.modules["pilosa_tpu.devtools.lockcheck"] = _lockcheck
    _lc_spec.loader.exec_module(_lockcheck)
    _lockcheck.install()


def pytest_sessionfinish(session, exitstatus):
    if not _LOCKCHECK:
        return
    out = os.environ.get("PILOSA_TPU_LOCKCHECK_OUT")
    if out:
        _lockcheck.write_report(out)
    fs = _lockcheck.findings()
    if fs:
        print("\n" + _lockcheck.report())


def pytest_configure(config):
    # Registered here (no pytest.ini in this repo) so tier-1's
    # `-m 'not slow'` selection works without unknown-mark warnings.
    config.addinivalue_line(
        "markers",
        "slow: timing-sensitive tests (real micro-batch windows, device "
        "benchmarks) excluded from the tier-1 CPU run",
    )
    config.addinivalue_line(
        "markers",
        "chaos: network-fault-injection cluster tests (tests/test_chaos.py)."
        " The deterministic seed-pinned smoke runs in tier-1; the"
        " randomized sweep is additionally marked slow (CHAOS_SMOKE=1"
        " shrinks it to the fast deterministic mode).",
    )


class FakeClock:
    """Deterministic monotonic clock for scheduler tests.

    Injectable wherever sched/ takes `clock` (Deadline, QueryScheduler):
    time() only moves when a test calls advance() or when a sleeper
    'sleeps' (sleep advances the clock immediately instead of blocking),
    so deadline tests run deterministically on CPU with zero wall-clock
    waits. Batcher window tests drive its `wait_window` hook instead."""

    def __init__(self, start: float = 1000.0):
        self._now = start
        self._lock = threading.Lock()

    def time(self) -> float:
        with self._lock:
            return self._now

    __call__ = time  # usable directly as the `clock` callable

    def advance(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)


@pytest.fixture
def fake_clock():
    return FakeClock()


@pytest.fixture(autouse=True)
def _release_engines(thread_leak_guard):
    """Close every ShardedQueryEngine a test constructs (directly or via
    a lazy Executor.engine) at teardown: the cold-gather pool's workers
    are non-daemon, and tests build engines ad hoc in dozens of places —
    tracking construction here keeps the thread-leak guard honest
    without threading an engine fixture through every test signature.
    Depending on the guard fixture orders finalization: engines release
    FIRST, the guard's census runs after. Double-close is safe
    (pool.shutdown is idempotent), so tests/servers that already close
    their executors are unaffected."""
    from pilosa_tpu.parallel import engine as engine_mod

    created = []
    orig_init = engine_mod.ShardedQueryEngine.__init__

    def tracking_init(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        created.append(self)

    engine_mod.ShardedQueryEngine.__init__ = tracking_init
    try:
        yield
    finally:
        engine_mod.ShardedQueryEngine.__init__ = orig_init
        for e in created:
            try:
                e.close()
            except Exception:
                pass


@pytest.fixture(autouse=True)
def thread_leak_guard(request):
    """Fail any test that leaves NON-DAEMON background threads running at
    teardown (un-shut-down executor/hedge/import pools, migration stream
    workers) — with the thread census printed so the leak is attributable
    to a thread, not a flaky downstream test. Daemon threads are exempt:
    the process can exit through them, and monitors/snapshotters are
    daemonized by design. A short grace lets threads that were ALREADY
    shutting down (pool.shutdown(wait=False)) finish their exit."""
    before = {t.ident for t in threading.enumerate()}
    yield

    def leaked():
        return [
            t for t in threading.enumerate()
            if t.ident not in before and not t.daemon and t.is_alive()
        ]

    remaining = leaked()
    deadline = time.monotonic() + 5.0
    while remaining and time.monotonic() < deadline:
        for t in remaining:
            t.join(timeout=0.2)
        remaining = leaked()
    if remaining:
        census = "\n".join(
            f"  - {t.name} (ident={t.ident}, daemon={t.daemon})"
            for t in remaining
        )
        pytest.fail(
            f"test leaked {len(remaining)} non-daemon background "
            f"thread(s) still running at teardown:\n{census}"
        )


@pytest.fixture(scope="session")
def tls_cert(tmp_path_factory):
    """Self-signed localhost cert/key pair, generated once per session."""
    d = tmp_path_factory.mktemp("tls")
    cert, key = d / "node.crt", d / "node.key"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True,
    )
    return str(cert), str(key)
