"""Protobuf wire format tests: content negotiation on /query and /import,
message compatibility with the reference's public.proto field layout."""

import urllib.request

import pytest

from pilosa_tpu.server.proto import (
    decode_query_response,
    encode_query_response,
    public_pb2 as pb,
)
from pilosa_tpu.server.server import Server


@pytest.fixture
def server(tmp_path):
    s = Server(data_dir=str(tmp_path / "srv"), cache_flush_interval=0)
    s.open()
    yield s
    s.close()


def _post(url, body, content_type=None, accept=None):
    req = urllib.request.Request(url, data=body, method="POST")
    if content_type:
        req.add_header("Content-Type", content_type)
    if accept:
        req.add_header("Accept", accept)
    with urllib.request.urlopen(req) as resp:
        return resp.read(), resp.headers.get("Content-Type")


def test_proto_query_roundtrip(server):
    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/p", b"{}")
    _post(f"http://{host}/index/p/field/f", b"{}")
    _post(f"http://{host}/index/p/query", b"Set(1, f=10) Set(2, f=10)")

    req = pb.QueryRequest()
    req.Query = "Row(f=10) Count(Row(f=10)) TopN(f, n=1)"
    data, ctype = _post(
        f"http://{host}/index/p/query",
        req.SerializeToString(),
        content_type="application/x-protobuf",
        accept="application/x-protobuf",
    )
    assert ctype == "application/x-protobuf"
    err, results = decode_query_response(data)
    assert err == ""
    row, count, pairs = results
    assert list(row.columns()) == [1, 2]
    assert count == 2
    assert [(p.id, p.count) for p in pairs] == [(10, 2)]


def test_proto_query_shards_restriction(server):
    from pilosa_tpu.constants import SHARD_WIDTH

    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/ps", b"{}")
    _post(f"http://{host}/index/ps/field/f", b"{}")
    _post(f"http://{host}/index/ps/query",
          f"Set(1, f=1) Set({SHARD_WIDTH + 1}, f=1)".encode())
    req = pb.QueryRequest()
    req.Query = "Count(Row(f=1))"
    req.Shards.extend([0])
    data, _ = _post(
        f"http://{host}/index/ps/query", req.SerializeToString(),
        content_type="application/x-protobuf", accept="application/x-protobuf",
    )
    _, results = decode_query_response(data)
    assert results[0] == 1  # only shard 0 counted


def test_proto_import(server):
    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/pi", b"{}")
    _post(f"http://{host}/index/pi/field/f", b"{}")
    req = pb.ImportRequest()
    req.Index = "pi"
    req.Field = "f"
    req.Shard = 0
    req.RowIDs.extend([1, 1, 2])
    req.ColumnIDs.extend([10, 20, 30])
    _post(
        f"http://{host}/index/pi/field/f/import",
        req.SerializeToString(),
        content_type="application/x-protobuf",
    )
    data, _ = _post(f"http://{host}/index/pi/query", b"Row(f=1)")
    import json

    assert json.loads(data)["results"][0]["columns"] == [10, 20]


def test_proto_import_values(server):
    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/pv", b"{}")
    _post(f"http://{host}/index/pv/field/v",
          b'{"options": {"type": "int", "min": 0, "max": 1000}}')
    req = pb.ImportValueRequest()
    req.Index = "pv"
    req.Field = "v"
    req.Shard = 0
    req.ColumnIDs.extend([1, 2])
    req.Values.extend([100, 200])
    _post(
        f"http://{host}/index/pv/field/v/import",
        req.SerializeToString(),
        content_type="application/x-protobuf",
    )
    data, _ = _post(f"http://{host}/index/pv/query", b"Sum(field=v)")
    import json

    assert json.loads(data)["results"][0] == {"value": 300, "count": 2}


def test_proto_attrs_roundtrip(server):
    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/pa", b"{}")
    _post(f"http://{host}/index/pa/field/f", b"{}")
    _post(f"http://{host}/index/pa/query",
          b'Set(1, f=3) SetRowAttrs(f, 3, color="red", n=7, active=true)')
    req = pb.QueryRequest()
    req.Query = "Row(f=3)"
    data, _ = _post(
        f"http://{host}/index/pa/query", req.SerializeToString(),
        content_type="application/x-protobuf", accept="application/x-protobuf",
    )
    _, results = decode_query_response(data)
    assert results[0].attrs == {"color": "red", "n": 7, "active": True}


def test_proto_error_response(server):
    host = f"localhost:{server.port}"
    req = pb.QueryRequest()
    req.Query = "Row(f=1)"
    r = urllib.request.Request(
        f"http://{host}/index/nosuch/query", data=req.SerializeToString(),
        method="POST",
    )
    r.add_header("Content-Type", "application/x-protobuf")
    r.add_header("Accept", "application/x-protobuf")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(r)
    err, results = decode_query_response(ei.value.read())
    assert "not found" in err


def test_encode_decode_helpers():
    from pilosa_tpu.core.cache import Pair
    from pilosa_tpu.core.row import Row
    from pilosa_tpu.executor import ValCount

    row = Row(columns=[1, 5])
    row.attrs = {"x": 1.5}
    results = [row, 7, True, [Pair(id=3, count=9, key="k")], ValCount(10, 2), None]
    err, decoded = decode_query_response(encode_query_response(results))
    assert err == ""
    assert list(decoded[0].columns()) == [1, 5]
    assert decoded[0].attrs == {"x": 1.5}
    assert decoded[1] == 7
    assert decoded[2] is True
    assert decoded[3][0].key == "k"
    assert decoded[4].val == 10
    assert decoded[5] is None
