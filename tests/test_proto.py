"""Protobuf wire format tests: content negotiation on /query and /import,
message compatibility with the reference's public.proto field layout."""

import urllib.request

import pytest

from pilosa_tpu.server.proto import (
    decode_query_response,
    encode_query_response,
    public_pb2 as pb,
)
from pilosa_tpu.server.server import Server


@pytest.fixture
def server(tmp_path):
    s = Server(data_dir=str(tmp_path / "srv"), cache_flush_interval=0)
    s.open()
    yield s
    s.close()


def _post(url, body, content_type=None, accept=None):
    req = urllib.request.Request(url, data=body, method="POST")
    if content_type:
        req.add_header("Content-Type", content_type)
    if accept:
        req.add_header("Accept", accept)
    with urllib.request.urlopen(req) as resp:
        return resp.read(), resp.headers.get("Content-Type")


def test_proto_query_roundtrip(server):
    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/p", b"{}")
    _post(f"http://{host}/index/p/field/f", b"{}")
    _post(f"http://{host}/index/p/query", b"Set(1, f=10) Set(2, f=10)")

    req = pb.QueryRequest()
    req.Query = "Row(f=10) Count(Row(f=10)) TopN(f, n=1)"
    data, ctype = _post(
        f"http://{host}/index/p/query",
        req.SerializeToString(),
        content_type="application/x-protobuf",
        accept="application/x-protobuf",
    )
    assert ctype == "application/x-protobuf"
    err, results = decode_query_response(data)
    assert err == ""
    row, count, pairs = results
    assert list(row.columns()) == [1, 2]
    assert count == 2
    assert [(p.id, p.count) for p in pairs] == [(10, 2)]


def test_proto_query_shards_restriction(server):
    from pilosa_tpu.constants import SHARD_WIDTH

    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/ps", b"{}")
    _post(f"http://{host}/index/ps/field/f", b"{}")
    _post(f"http://{host}/index/ps/query",
          f"Set(1, f=1) Set({SHARD_WIDTH + 1}, f=1)".encode())
    req = pb.QueryRequest()
    req.Query = "Count(Row(f=1))"
    req.Shards.extend([0])
    data, _ = _post(
        f"http://{host}/index/ps/query", req.SerializeToString(),
        content_type="application/x-protobuf", accept="application/x-protobuf",
    )
    _, results = decode_query_response(data)
    assert results[0] == 1  # only shard 0 counted


def test_proto_import(server):
    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/pi", b"{}")
    _post(f"http://{host}/index/pi/field/f", b"{}")
    req = pb.ImportRequest()
    req.Index = "pi"
    req.Field = "f"
    req.Shard = 0
    req.RowIDs.extend([1, 1, 2])
    req.ColumnIDs.extend([10, 20, 30])
    _post(
        f"http://{host}/index/pi/field/f/import",
        req.SerializeToString(),
        content_type="application/x-protobuf",
    )
    data, _ = _post(f"http://{host}/index/pi/query", b"Row(f=1)")
    import json

    assert json.loads(data)["results"][0]["columns"] == [10, 20]


def test_proto_import_values(server):
    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/pv", b"{}")
    _post(f"http://{host}/index/pv/field/v",
          b'{"options": {"type": "int", "min": 0, "max": 1000}}')
    req = pb.ImportValueRequest()
    req.Index = "pv"
    req.Field = "v"
    req.Shard = 0
    req.ColumnIDs.extend([1, 2])
    req.Values.extend([100, 200])
    _post(
        f"http://{host}/index/pv/field/v/import",
        req.SerializeToString(),
        content_type="application/x-protobuf",
    )
    data, _ = _post(f"http://{host}/index/pv/query", b"Sum(field=v)")
    import json

    assert json.loads(data)["results"][0] == {"value": 300, "count": 2}


def test_proto_attrs_roundtrip(server):
    host = f"localhost:{server.port}"
    _post(f"http://{host}/index/pa", b"{}")
    _post(f"http://{host}/index/pa/field/f", b"{}")
    _post(f"http://{host}/index/pa/query",
          b'Set(1, f=3) SetRowAttrs(f, 3, color="red", n=7, active=true)')
    req = pb.QueryRequest()
    req.Query = "Row(f=3)"
    data, _ = _post(
        f"http://{host}/index/pa/query", req.SerializeToString(),
        content_type="application/x-protobuf", accept="application/x-protobuf",
    )
    _, results = decode_query_response(data)
    assert results[0].attrs == {"color": "red", "n": 7, "active": True}


def test_proto_error_response(server):
    host = f"localhost:{server.port}"
    req = pb.QueryRequest()
    req.Query = "Row(f=1)"
    r = urllib.request.Request(
        f"http://{host}/index/nosuch/query", data=req.SerializeToString(),
        method="POST",
    )
    r.add_header("Content-Type", "application/x-protobuf")
    r.add_header("Accept", "application/x-protobuf")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(r)
    err, results = decode_query_response(ei.value.read())
    assert "not found" in err


def test_encode_decode_helpers():
    from pilosa_tpu.core.cache import Pair
    from pilosa_tpu.core.row import Row
    from pilosa_tpu.executor import ValCount

    row = Row(columns=[1, 5])
    row.attrs = {"x": 1.5}
    results = [row, 7, True, [Pair(id=3, count=9, key="k")], ValCount(10, 2), None]
    err, decoded = decode_query_response(encode_query_response(results))
    assert err == ""
    assert list(decoded[0].columns()) == [1, 5]
    assert decoded[0].attrs == {"x": 1.5}
    assert decoded[1] == 7
    assert decoded[2] is True
    assert decoded[3][0].key == "k"
    assert decoded[4].val == 10
    assert decoded[5] is None


def test_golden_query_response_bytes():
    """Pin the public QueryResponse WIRE BYTES against the reference
    schema (internal/public.proto:56-69 QueryResponse/QueryResult, field
    numbers and proto3 packed/varint rules applied by hand below). The
    internal node-to-node plane is deliberately NOT reference-compatible
    (docs/architecture.md "Interoperability"); this golden guarantees the
    PUBLIC plane stays byte-compatible — a reference protobuf client must
    parse our responses forever."""
    from pilosa_tpu.core.cache import Pair
    from pilosa_tpu.core.row import Row
    from pilosa_tpu.executor import ValCount

    row = Row(columns=[1, 2**20 + 1])  # crosses a varint width
    got = encode_query_response([7, row, [Pair(id=10, count=3)],
                                 ValCount(val=-2, count=4), True])

    golden = bytes.fromhex(
        # QueryResponse.Results is field 2 (tag 0x12), one length-
        # delimited QueryResult each. QueryResult fields: Row=1, N=2,
        # Pairs=3, Changed=4, ValCount=5, Type=6 (serialized in field-
        # number order by canonical protobuf encoders).
        "12 04"        # Results[0], 4 bytes: Count result
        "10 07"        #   N=7        (field 2 varint)
        "30 04"        #   Type=4     (TYPE_UINT64, http/handler.go tag)
        "12 0a"        # Results[1], 10 bytes: Row result
        "0a 06"        #   Row=       (field 1, message, 6 bytes)
        "0a 04"        #     Columns= (field 1, packed varints, 4 bytes)
        "01"           #       1
        "81 80 40"     #       1048577 = 2^20+1 as varint
        "30 01"        #   Type=1     (TYPE_ROW)
        "12 08"        # Results[2], 8 bytes: Pairs result
        "1a 04"        #   Pairs[0]=  (field 3, message, 4 bytes)
        "08 0a"        #     ID=10    (field 1 varint)
        "10 03"        #     Count=3  (field 2 varint)
        "30 02"        #   Type=2     (TYPE_PAIRS)
        "12 11"        # Results[3], 17 bytes: ValCount result
        "2a 0d"        #   ValCount=  (field 5, message, 13 bytes)
        "08" + "fe" + "ff" * 8 + "01"       # Val=-2 (int64 varint, 10B)
        "10 04"        #     Count=4  (field 2 varint)
        "30 03"        #   Type=3     (TYPE_VALCOUNT)
        "12 04"        # Results[4], 4 bytes: Changed result
        "20 01"        #   Changed=true (field 4 varint)
        "30 05"        #   Type=5     (TYPE_BOOL)
        .replace(" ", "")
    )
    assert got == golden, (got.hex(), golden.hex())
    # And it round-trips through the decoder.
    decoded = pb.QueryResponse()
    decoded.ParseFromString(got)
    assert decoded.Results[0].N == 7
    assert list(decoded.Results[1].Row.Columns) == [1, 2**20 + 1]
    assert decoded.Results[3].ValCount.Val == -2
