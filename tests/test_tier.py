"""Tiered plane storage (pilosa_tpu/tier/): the HBM ↔ host-RAM ↔ disk
residency manager behind the engine's device caches.

The tentpole invariants under test: a demote-to-host/disk → re-promote
cycle is bit-exact against a cold gather (fingerprint equality included);
delta-fold-on-promotion matches a full regather after interleaved writes;
a concurrent query during demotion sees either tier correctly (no torn
plane); and a corrupt spill file degrades to a regather, never to a query
error. Plus the satellite surfaces: the oversized-entry policy and the
memo eviction counters in the engine byte caches, and the env > [engine]
> [tier] > default budget resolution.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.constants import SHARD_WIDTH, WORDS_PER_ROW
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.errors import CorruptFragmentError
from pilosa_tpu.parallel import EngineConfig
from pilosa_tpu.parallel.engine import Leaf, ShardedQueryEngine
from pilosa_tpu.pql.parser import parse
from pilosa_tpu.storage.bitmap import decode_plane_words
from pilosa_tpu.tier import TierConfig
from pilosa_tpu.tier.manager import TierManager

N_WORDS64 = WORDS_PER_ROW // 2  # decode_plane_words speaks 64-bit words


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def plant(holder, n_shards=2, n_rows=8, per_row=300, seed=7, index="i"):
    idx = holder.create_index_if_not_exists(index)
    fld = idx.create_field_if_not_exists("f")
    rng = np.random.default_rng(seed)
    expected = {}
    for row in range(n_rows):
        cols = []
        for s in range(n_shards):
            local = rng.choice(SHARD_WIDTH, size=per_row, replace=False)
            cols.extend(int(s * SHARD_WIDTH + c) for c in local)
        fld.import_bits([row] * len(cols), cols)
        expected[row] = set(cols)
    return fld, expected


def tiny_engine(holder, n_keep_planes, n_shards, tier=None, **tier_kw):
    """Engine whose leaf cache holds only `n_keep_planes` planes, so every
    sweep over more planes than that evicts (and demotes, when a tier
    config enables the manager)."""
    plane_bytes = n_shards * WORDS_PER_ROW * 4
    if tier is None:
        tier_kw.setdefault("host_bytes", 1 << 28)
        tier_kw.setdefault("prefetch_interval", 0)
        tier = TierConfig(**tier_kw)
    return ShardedQueryEngine(
        holder,
        config=EngineConfig(leaf_cache_bytes=n_keep_planes * plane_bytes),
        tier_config=tier,
    )


def sweep(engine, index, calls, shards, rows):
    return [int(np.asarray(engine.count_async(index, calls[r], shards)))
            for r in rows]


# ------------------------------------------------------- plane-section codec


class TestPlaneCodec:
    def _roundtrip(self, holder, cols):
        idx = holder.create_index_if_not_exists("codec")
        fld = idx.create_field_if_not_exists(f"f{len(cols)}_{hash(tuple(cols)) & 0xFFFF}")
        if len(cols):
            fld.import_bits([0] * len(cols), sorted(int(c) for c in cols))
        frag = holder.fragment("codec", fld.name, "standard", 0)
        if frag is None:  # empty row: decode of an empty bitmap
            from pilosa_tpu.storage.bitmap import Bitmap

            data = Bitmap().to_bytes()
            got = decode_plane_words(data, N_WORDS64)
            assert not got.any()
            return
        frag.storage.optimize()  # settle forms (runs/bitmaps where smaller)
        data, fp = frag.row_compressed(0)
        want = frag.plane_np(0)
        got = decode_plane_words(data, N_WORDS64).view(np.uint32)
        np.testing.assert_array_equal(got, want)
        assert fp == (frag.incarnation, frag.generation)

    def test_array_containers(self, holder):
        rng = np.random.default_rng(3)
        self._roundtrip(holder, rng.choice(SHARD_WIDTH, 700, replace=False))

    def test_run_containers(self, holder):
        self._roundtrip(
            holder,
            list(range(1000, 9000)) + list(range(70000, 70100))
            + [0, 63, 64, SHARD_WIDTH - 1])

    def test_bitmap_containers(self, holder):
        rng = np.random.default_rng(4)
        self._roundtrip(holder, rng.choice(1 << 17, 40000, replace=False))

    def test_word_boundary_bits(self, holder):
        # Run endpoints landing exactly on 64-bit word edges exercise the
        # first/middle/last mask arithmetic.
        self._roundtrip(holder, list(range(64, 256)) + [63, 256, 319])

    def test_empty(self, holder):
        self._roundtrip(holder, [])

    def test_trailing_bytes_ignored(self, holder):
        fld, _ = plant(holder, n_shards=1, n_rows=1)
        frag = holder.fragment("i", "f", "standard", 0)
        data, _ = frag.row_compressed(0)
        got = decode_plane_words(data + b"opslog-junk", N_WORDS64)
        np.testing.assert_array_equal(
            got, decode_plane_words(data, N_WORDS64))

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda d: d[:4],  # truncated header
            lambda d: b"XX" + d[2:],  # bad magic
            lambda d: d[: len(d) // 2],  # truncated payload
        ],
    )
    def test_corrupt_raises_typed(self, holder, mutate):
        fld, _ = plant(holder, n_shards=1, n_rows=1)
        frag = holder.fragment("i", "f", "standard", 0)
        data, _ = frag.row_compressed(0)
        with pytest.raises(CorruptFragmentError):
            decode_plane_words(mutate(data), N_WORDS64)

    def test_container_beyond_plane_raises(self, holder):
        # A container key past the plane's words is corruption, not a
        # silent truncation.
        from pilosa_tpu.storage.bitmap import Bitmap

        b = Bitmap(np.array([5], dtype=np.uint64))
        data = b.to_bytes()
        with pytest.raises(CorruptFragmentError):
            decode_plane_words(data, 0)

    def test_partial_plane_container_decodes(self):
        """Exotic SHARD_WIDTH < 2^16: the plane is smaller than one
        container, whose in-plane bits must decode (and bits beyond the
        plane must raise, not scatter out of bounds)."""
        from pilosa_tpu.storage.bitmap import Bitmap

        n_words = 8  # a 512-bit plane
        b = Bitmap(np.array([0, 5, 64, 511], dtype=np.uint64))
        got = decode_plane_words(b.to_bytes(), n_words)
        want = np.zeros(n_words, dtype=np.uint64)
        want[0] = (1 << 0) | (1 << 5)
        want[1] = 1
        want[7] = 1 << 63
        np.testing.assert_array_equal(got, want)
        with pytest.raises(CorruptFragmentError):
            decode_plane_words(
                Bitmap(np.array([512], dtype=np.uint64)).to_bytes(), n_words)
        # Run form beyond the plane is equally typed corruption.
        dense = Bitmap(np.arange(500, 520, dtype=np.uint64))
        dense.optimize()
        with pytest.raises(CorruptFragmentError):
            decode_plane_words(dense.to_bytes(), n_words)


# --------------------------------------------------- demote/promote (host)


class TestHostTierRoundTrip:
    def test_repromotion_is_bit_exact_vs_cold_gather(self, holder):
        n_rows, n_shards = 8, 2
        fld, expected = plant(holder, n_shards, n_rows)
        shards = tuple(range(n_shards))
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(n_rows)}
        engine = tiny_engine(holder, 3, n_shards)
        try:
            # Cold sweep (evicts+demotes), then re-sweep from the tier.
            got1 = sweep(engine, "i", calls, shards, range(n_rows))
            engine.tier.drain()
            base = dict(engine.counters)
            got2 = sweep(engine, "i", calls, shards, range(n_rows))
            assert got1 == got2 == [len(expected[r]) for r in range(n_rows)]
            assert engine.counters["leaf_misses"] == base["leaf_misses"], \
                "a warm tier must absorb every HBM miss"
            assert engine.counters["leaf_tier_hits"] > base["leaf_tier_hits"]

            # Fingerprint-equality check on the actual device planes: the
            # promoted tensor must be byte-identical to a cold gather by a
            # tierless engine.
            cold = ShardedQueryEngine(
                holder, config=EngineConfig(),
                tier_config=TierConfig(host_bytes=0, disk_bytes=0))
            try:
                for r in range(n_rows):
                    leaf = Leaf("f", "standard", r)
                    a = np.asarray(engine._gather_leaf("i", leaf, shards))
                    b = np.asarray(cold._gather_leaf("i", leaf, shards))
                    np.testing.assert_array_equal(a, b)
            finally:
                cold.close()
        finally:
            engine.close()

    def test_delta_fold_on_promotion_matches_regather(self, holder):
        n_rows, n_shards = 8, 2
        fld, expected = plant(holder, n_shards, n_rows)
        shards = tuple(range(n_shards))
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(n_rows)}
        engine = tiny_engine(holder, 3, n_shards)
        try:
            sweep(engine, "i", calls, shards, range(n_rows))
            engine.tier.drain()
            # Interleaved writes to every plane — including demoted ones.
            for r in range(n_rows):
                col = (r * 977) % SHARD_WIDTH
                if fld.set_bit(r, col):
                    expected[r].add(col)
                rm = next(iter(expected[r]))
                fld.clear_bit(r, rm)
                expected[r].discard(rm)
            base = dict(engine.counters)
            got = sweep(engine, "i", calls, shards, range(n_rows))
            assert got == [len(expected[r]) for r in range(n_rows)]
            # Planes whose journals stayed within the delta bound must not
            # have paid a full regather: folds (demoted) or delta hits
            # (still resident) only.
            assert engine.counters["leaf_misses"] == base["leaf_misses"]
            assert engine.tier.counters["delta_folds"] > 0
        finally:
            engine.close()

    def test_journal_overflow_walks_that_shard_only(self, tmp_path):
        h = Holder(str(tmp_path / "ovf"), delta_journal_ops=8)
        h.open()
        try:
            fld, expected = plant(h, 2, 4)
            shards = (0, 1)
            calls = {r: parse(f"Row(f={r})").calls[0] for r in range(4)}
            engine = tiny_engine(h, 1, 2)
            try:
                sweep(engine, "i", calls, shards, range(4))
                engine.tier.drain()
                # Blow past the journal bound on row 0 / shard 0 only.
                for k in range(16):
                    col = 64 * k
                    if fld.set_bit(0, col):
                        expected[0].add(col)
                got = sweep(engine, "i", calls, shards, range(4))
                assert got == [len(expected[r]) for r in range(4)]
                assert engine.tier.counters["shard_walks"] >= 1
            finally:
                engine.close()
        finally:
            h.close()

    def test_recreated_index_never_serves_stale_blob(self, holder):
        fld, _ = plant(holder, 2, 4)
        shards = (0, 1)
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(4)}
        engine = tiny_engine(holder, 1, 2)
        try:
            sweep(engine, "i", calls, shards, range(4))
            engine.tier.drain()
            holder.delete_index("i")
            idx = holder.create_index("i")
            f2 = idx.create_field("f")
            f2.set_bit(0, 5)
            f2.set_bit(0, SHARD_WIDTH + 9)
            got = int(np.asarray(engine.count_async("i", calls[0], shards)))
            assert got == 2
        finally:
            engine.close()

    def test_inclusive_host_tier_skips_unchanged_recapture(self, holder):
        """Steady-state read churn: evict → promote → evict again with no
        writes in between must not re-serialize the plane."""
        fld, _ = plant(holder, 2, 8)
        shards = (0, 1)
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(8)}
        engine = tiny_engine(holder, 2, 2)
        try:
            sweep(engine, "i", calls, shards, range(8))
            engine.tier.drain()
            sweep(engine, "i", calls, shards, range(8))
            engine.tier.drain()
            assert engine.tier.counters["demotions_skipped"] > 0
        finally:
            engine.close()


# ------------------------------------------------------------- concurrency


class TestConcurrency:
    def test_no_torn_plane_during_demotion_churn(self, holder):
        """Queries racing demotions (the background worker serializing
        live containers), forced demote churn, and concurrent writes must
        see every plane at SOME valid state — counts on the unwritten
        rows are always exact, never torn.

        Device dispatch stays on ONE thread (concurrent sharded dispatch
        on the 8-device CPU test mesh is a jax-level hazard the scheduler
        serializes in production); the concurrency under test is the tier
        manager's demote worker + direct demote churn + fragment writes
        against that query stream."""
        n_rows, n_shards = 10, 2
        fld, expected = plant(holder, n_shards, n_rows)
        shards = tuple(range(n_shards))
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(n_rows)}
        engine = tiny_engine(holder, 2, n_shards)
        stop = threading.Event()
        errors = []

        def demote_churn():
            # Re-queue every key for demotion constantly, including keys
            # that are HBM-resident or mid-promotion.
            while not stop.is_set():
                for r in range(n_rows):
                    engine.tier.demote(("i", Leaf("f", "standard", r),
                                        shards))
                time.sleep(0.001)

        def write_churn():
            # Writes land on rows 2.. only, so rows 0/1 keep a stable
            # expected count while their planes still churn through the
            # tiers.
            k = 0
            while not stop.is_set():
                fld.set_bit(2 + (k % (n_rows - 2)), (k * 131) % SHARD_WIDTH)
                k += 1
                time.sleep(0.0005)

        threads = [threading.Thread(target=demote_churn),
                   threading.Thread(target=write_churn)]
        for t in threads:
            t.start()
        try:
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline and not errors:
                for r in range(n_rows):
                    got = int(np.asarray(
                        engine.count_async("i", calls[r], shards)))
                    if r < 2 and got != len(expected[r]):
                        errors.append((r, got, len(expected[r])))
                    elif got < len(expected[r]):  # writes only ADD bits
                        errors.append((r, got, len(expected[r])))
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
            engine.close()
        assert not errors, errors[:3]


# ---------------------------------------------------------------- disk tier


class TestDiskTier:
    def _spill_engine(self, holder, tmp_path, host_planes=1):
        plane_bytes = 2 * WORDS_PER_ROW * 4
        # Host tier big enough for ~1 compressed plane only, so demotions
        # cascade to disk. Compressed planes here are ~2-3 KiB.
        return tiny_engine(
            holder, 1, 2,
            tier=TierConfig(host_bytes=4096, disk_bytes=1 << 22,
                            disk_path=str(tmp_path / "spill"),
                            prefetch_interval=0))

    def test_disk_round_trip_bit_exact(self, holder, tmp_path):
        n_rows = 6
        fld, expected = plant(holder, 2, n_rows)
        shards = (0, 1)
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(n_rows)}
        engine = self._spill_engine(holder, tmp_path)
        try:
            got1 = sweep(engine, "i", calls, shards, range(n_rows))
            engine.tier.drain()
            snap = engine.tier.snapshot()
            assert snap["demotions_disk"] > 0
            assert os.listdir(tmp_path / "spill")
            got2 = sweep(engine, "i", calls, shards, range(n_rows))
            assert got1 == got2 == [len(expected[r]) for r in range(n_rows)]
            assert engine.tier.snapshot()["promotions_disk"] > 0
        finally:
            engine.close()

    def test_corrupt_spill_regathers_not_errors(self, holder, tmp_path):
        n_rows = 6
        fld, expected = plant(holder, 2, n_rows)
        shards = (0, 1)
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(n_rows)}
        engine = self._spill_engine(holder, tmp_path)
        try:
            sweep(engine, "i", calls, shards, range(n_rows))
            engine.tier.drain()
            spill_dir = tmp_path / "spill"
            files = sorted(os.listdir(spill_dir))
            assert files
            for name in files:  # flip bytes in EVERY spill file
                p = spill_dir / name
                raw = bytearray(p.read_bytes())
                raw[len(raw) // 2] ^= 0xFF
                p.write_bytes(bytes(raw))
            got = sweep(engine, "i", calls, shards, range(n_rows))
            assert got == [len(expected[r]) for r in range(n_rows)]
            snap = engine.tier.snapshot()
            # Every corrupted file was detected exactly once and deleted
            # (the re-sweep's own evictions may re-spill under the same
            # deterministic names — those are fresh, valid images).
            assert snap["corrupt_spills"] == len(files)
        finally:
            engine.close()

    def test_missing_spill_file_regathers(self, holder, tmp_path):
        n_rows = 6
        fld, expected = plant(holder, 2, n_rows)
        shards = (0, 1)
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(n_rows)}
        engine = self._spill_engine(holder, tmp_path)
        try:
            sweep(engine, "i", calls, shards, range(n_rows))
            engine.tier.drain()
            for name in os.listdir(tmp_path / "spill"):
                os.remove(tmp_path / "spill" / name)
            got = sweep(engine, "i", calls, shards, range(n_rows))
            assert got == [len(expected[r]) for r in range(n_rows)]
        finally:
            engine.close()

    def test_disk_budget_evicts_oldest_spill(self, holder, tmp_path):
        fld, _ = plant(holder, 2, 8)
        shards = (0, 1)
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(8)}
        engine = tiny_engine(
            holder, 1, 2,
            tier=TierConfig(host_bytes=4096, disk_bytes=6000,
                            disk_path=str(tmp_path / "spill"),
                            prefetch_interval=0))
        try:
            sweep(engine, "i", calls, shards, range(8))
            engine.tier.drain()
            snap = engine.tier.snapshot()
            assert snap["disk_bytes"] <= 6000
            assert snap["disk_evictions"] > 0
        finally:
            engine.close()


# ----------------------------------------------------- predictive prefetch


class TestPrefetch:
    def test_hot_index_promoted_before_query(self, holder):
        n_rows = 6
        fld, expected = plant(holder, 2, n_rows)
        shards = (0, 1)
        calls = {r: parse(f"Row(f={r})").calls[0] for r in range(n_rows)}
        traffic = {"n": 1}
        engine = ShardedQueryEngine(
            holder,
            config=EngineConfig(
                leaf_cache_bytes=4 * n_rows * 2 * WORDS_PER_ROW * 4),
            tier_config=TierConfig(host_bytes=1 << 28,
                                   prefetch_interval=0.01,
                                   prefetch_batch=8),
            traffic_fn=lambda: {"i": traffic["n"]})
        try:
            for r in range(n_rows):
                engine.tier.demote(("i", Leaf("f", "standard", r), shards))
            engine.tier.drain()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                traffic["n"] += 1
                if engine.tier.snapshot()["prefetch_promotions"] >= n_rows:
                    break
                time.sleep(0.02)
            assert engine.tier.snapshot()["prefetch_promotions"] >= n_rows
            base = dict(engine.counters)
            got = sweep(engine, "i", calls, shards, range(n_rows))
            assert got == [len(expected[r]) for r in range(n_rows)]
            # Every plane was already HBM-resident: zero query-path work.
            assert engine.counters["leaf_misses"] == base["leaf_misses"]
            assert engine.counters["leaf_tier_hits"] == base["leaf_tier_hits"]
            assert engine.tier.snapshot()["prefetch_hits"] >= 1
        finally:
            engine.close()

    def test_cold_index_not_promoted(self, holder):
        fld, _ = plant(holder, 2, 4)
        shards = (0, 1)
        engine = ShardedQueryEngine(
            holder,
            config=EngineConfig(leaf_cache_bytes=1 << 26),
            tier_config=TierConfig(host_bytes=1 << 28,
                                   prefetch_interval=0.01),
            traffic_fn=lambda: {"other-index": 1})  # never increases
        try:
            for r in range(4):
                engine.tier.demote(("i", Leaf("f", "standard", r), shards))
            engine.tier.drain()
            time.sleep(0.2)
            assert engine.tier.snapshot()["prefetch_promotions"] == 0
        finally:
            engine.close()

    def test_prefetch_never_evicts(self):
        m = TierManager(holder=None, config=TierConfig(
            host_bytes=1 << 20, prefetch_interval=0))
        promoted = []
        m.bind(promote_fn=lambda k: promoted.append(k) or True,
               headroom_fn=lambda: 0,  # no free HBM
               resident_fn=lambda k: False)
        # Seed a fake host entry and run one sweep body inline.
        from pilosa_tpu.tier.manager import _PlaneEntry

        with m._lock:
            m._host[("i", Leaf("f", "standard", 0), (0,))] = _PlaneEntry(
                [(0, 0)], [b"x"])
        # One manual sweep: headroom 0 → nothing promoted.
        m.config.prefetch_interval = 0.01
        m._stop.clear()
        t = threading.Thread(target=m._prefetch_loop, daemon=True)
        t.start()
        time.sleep(0.1)
        m.close()
        assert promoted == []


# ------------------------------- engine byte-cache policies (satellites)


class TestByteCachePolicies:
    def test_oversized_entry_admitted_alone_and_counted(self, holder):
        plant(holder, 1, 1)
        engine = ShardedQueryEngine(
            holder, tier_config=TierConfig(host_bytes=0, disk_bytes=0))
        try:
            cache, used, budget = {}, 0, 100
            evicted = []
            with engine._lock:
                used = engine._byte_cache_put(
                    cache, "a", ((), np.zeros(40, np.uint8)), budget, used,
                    "leaf_evictions", evicted)
                used = engine._byte_cache_put(
                    cache, "b", ((), np.zeros(40, np.uint8)), budget, used,
                    "leaf_evictions", evicted)
                used = engine._byte_cache_put(
                    cache, "huge", ((), np.zeros(500, np.uint8)), budget,
                    used, "leaf_evictions", evicted)
            # Admitted ALONE: everything else evicted, accounting exact.
            assert list(cache) == ["huge"]
            assert used == 500
            assert engine.counters["oversized_admits"] == 1
            assert evicted == ["a", "b"]
            # The next insert immediately evicts back under budget.
            with engine._lock:
                used = engine._byte_cache_put(
                    cache, "c", ((), np.zeros(60, np.uint8)), budget, used,
                    "leaf_evictions", evicted)
            assert "huge" not in cache and used == 60
            assert "huge" in evicted
        finally:
            engine.close()

    def test_memo_and_aux_eviction_counters(self, holder):
        plant(holder, 1, 4)
        engine = ShardedQueryEngine(
            holder,
            config=EngineConfig(memo_entries=2, aux_memo_entries=2),
            tier_config=TierConfig(host_bytes=0, disk_bytes=0))
        try:
            shards = (0,)
            for r in range(4):
                engine.count("i", parse(f"Row(f={r})").calls[0], shards)
            assert engine.counters["memo_evictions"] >= 2
            for k in range(4):
                engine._aux_store((("k", k), ("fp",)), ("fp",), k)
            assert engine.counters["aux_evictions"] >= 2
        finally:
            engine.close()


# ------------------------------------------- budgets + config resolution


class TestBudgetResolution:
    def _mk(self, holder, **kw):
        return ShardedQueryEngine(
            holder, tier_config=TierConfig(host_bytes=0, disk_bytes=0), **kw)

    def test_engine_config_budgets_apply(self, holder):
        plant(holder, 1, 1)
        engine = self._mk(holder, config=EngineConfig(
            leaf_cache_bytes=111, stack_cache_bytes=222, memo_entries=33,
            aux_memo_entries=44))
        try:
            assert engine.budgets["leaf_cache_bytes"] == 111
            assert engine.budgets["stack_cache_bytes"] == 222
            assert engine.budgets["memo_entries"] == 33
            assert engine.budgets["aux_memo_entries"] == 44
        finally:
            engine.close()

    def test_legacy_env_beats_config(self, holder, monkeypatch):
        plant(holder, 1, 1)
        monkeypatch.setenv("PILOSA_LEAF_CACHE_BYTES", "777")
        monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")
        engine = self._mk(holder, config=EngineConfig(
            leaf_cache_bytes=111, memo_entries=33))
        try:
            assert engine.budgets["leaf_cache_bytes"] == 777
            # env can express "0 entries"; config 0 means auto.
            assert engine.budgets["memo_entries"] == 0
        finally:
            engine.close()

    def test_tier_hbm_bytes_splits_device_budget(self, holder):
        plant(holder, 1, 1)
        engine = ShardedQueryEngine(
            holder,
            tier_config=TierConfig(hbm_bytes=1 << 20, host_bytes=0,
                                   disk_bytes=0))
        try:
            assert engine.budgets["leaf_cache_bytes"] == 1 << 19
            assert engine.budgets["stack_cache_bytes"] == 1 << 19
        finally:
            engine.close()

    def test_explicit_engine_budget_beats_hbm_split(self, holder):
        plant(holder, 1, 1)
        engine = ShardedQueryEngine(
            holder, config=EngineConfig(leaf_cache_bytes=12345),
            tier_config=TierConfig(hbm_bytes=1 << 20, host_bytes=0,
                                   disk_bytes=0))
        try:
            assert engine.budgets["leaf_cache_bytes"] == 12345
            assert engine.budgets["stack_cache_bytes"] == 1 << 19
        finally:
            engine.close()

    def test_tier_config_validate(self):
        with pytest.raises(ValueError):
            TierConfig(host_bytes=-1).validate()
        with pytest.raises(ValueError):
            TierConfig(prefetch_interval=-0.1).validate()
        with pytest.raises(ValueError):
            TierConfig(prefetch_batch=0).validate()
        assert not TierConfig(host_bytes=0, disk_bytes=0).enabled()
        assert TierConfig(host_bytes=1).enabled()
        # Disk-only needs a path to be usable.
        assert not TierConfig(host_bytes=0, disk_bytes=1).enabled()
        assert TierConfig(host_bytes=0, disk_bytes=1, disk_path="/x").enabled()

    def test_config_toml_env_flags(self, tmp_path, monkeypatch):
        from pilosa_tpu.config import Config

        p = tmp_path / "c.toml"
        p.write_text(
            "[tier]\nhbm-bytes = 10\nhost-bytes = 20\ndisk-bytes = 30\n"
            'disk-path = "/tmp/sp"\nprefetch-interval = 0.5\n'
            "prefetch-batch = 9\n"
            "[engine]\nleaf-cache-bytes = 40\nstack-cache-bytes = 50\n"
            "memo-entries = 60\naux-memo-entries = 70\n")
        cfg = Config.load(str(p))
        assert (cfg.tier.hbm_bytes, cfg.tier.host_bytes,
                cfg.tier.disk_bytes) == (10, 20, 30)
        assert cfg.tier.disk_path == "/tmp/sp"
        assert cfg.tier.prefetch_interval == 0.5
        assert cfg.tier.prefetch_batch == 9
        assert cfg.engine.leaf_cache_bytes == 40
        assert cfg.engine.aux_memo_entries == 70
        # env beats file
        monkeypatch.setenv("PILOSA_TPU_TIER_HOST_BYTES", "21")
        monkeypatch.setenv("PILOSA_TPU_ENGINE_MEMO_ENTRIES", "61")
        cfg = Config.load(str(p))
        assert cfg.tier.host_bytes == 21
        assert cfg.engine.memo_entries == 61
        # flags beat env
        cfg = Config.load(str(p), flags={"tier_host_bytes": 22,
                                         "engine_memo_entries": 62})
        assert cfg.tier.host_bytes == 22
        assert cfg.engine.memo_entries == 62
        # round-trips through to_toml
        dumped = cfg.to_toml()
        assert "[tier]" in dumped and "host-bytes = 22" in dumped
        assert "leaf-cache-bytes = 40" in dumped

    def test_cli_flags_parse(self):
        from pilosa_tpu.cli import build_parser

        ns = build_parser().parse_args([
            "server", "--tier-hbm-bytes", "1", "--tier-host-bytes", "2",
            "--tier-disk-bytes", "3", "--tier-disk-path", "/s",
            "--tier-prefetch-interval", "0.25", "--tier-prefetch-batch",
            "5", "--engine-leaf-cache-bytes", "6",
            "--engine-stack-cache-bytes", "7", "--engine-memo-entries",
            "8", "--engine-aux-memo-entries", "9"])
        assert ns.tier_hbm_bytes == 1 and ns.tier_host_bytes == 2
        assert ns.tier_disk_bytes == 3 and ns.tier_disk_path == "/s"
        assert ns.tier_prefetch_interval == 0.25
        assert ns.tier_prefetch_batch == 5
        assert ns.engine_leaf_cache_bytes == 6
        assert ns.engine_stack_cache_bytes == 7
        assert ns.engine_memo_entries == 8
        assert ns.engine_aux_memo_entries == 9


# ------------------------------------------------- scheduler traffic signal


def test_scheduler_traffic_evicts_by_recency_not_count():
    """A full traffic table must evict the least-recently-touched index,
    never the lowest lifetime count — otherwise newly-created busy
    indexes would perpetually evict each other while idle-but-
    historically-hot indexes squat the table."""
    from pilosa_tpu.sched import QueryScheduler, SchedulerConfig

    sched = QueryScheduler(SchedulerConfig())
    sched._index_traffic_max = 4
    for i in range(4):
        for _ in range(100):
            sched.note_index(f"old{i}")
    # Two new actively-queried indexes alternate; the OLD idle entries
    # must be evicted, and the active pair must both survive.
    for _ in range(5):
        sched.note_index("a")
        sched.note_index("b")
    t = sched.index_traffic()
    assert t["a"] == 5 and t["b"] == 5, t
    assert len(t) == 4


# ----------------------------------------------------- server observability


def test_debug_vars_tier_group_and_budgets(tmp_path):
    from pilosa_tpu.server.server import Server
    from pilosa_tpu.tier import TierConfig as TC

    s = Server(data_dir=str(tmp_path / "node"), cache_flush_interval=0,
               member_monitor_interval=0,
               tier_config=TC(host_bytes=1 << 24, disk_bytes=1 << 20))
    s.open()
    try:
        # Disk path defaulted under the data dir.
        assert s.executor.tier_config.disk_path.endswith("tier-spill")
        # Traffic signal wired scheduler → executor → engine.
        assert s.executor.tier_traffic_fn is not None
        s.api.create_index("dv")
        s.api.create_field("dv", "f")
        s.api.query("dv", "Set(3, f=1)")
        s.api.query("dv", "Count(Row(f=1))")
        with urllib.request.urlopen(
                f"http://localhost:{s.port}/debug/vars") as r:
            dv = json.load(r)
        tier = dv["tier"]
        for key in ("host_bytes", "host_entries", "disk_bytes",
                    "demotions_host", "promotions_host", "delta_folds",
                    "prefetch_promotions", "prefetch_hits",
                    "corrupt_spills", "host_budget", "disk_budget"):
            assert key in tier, key
        budgets = dv["engine_budgets"]
        for key in ("leaf_cache_bytes", "stack_cache_bytes",
                    "memo_entries", "aux_memo_entries"):
            assert key in budgets, key
        # The scheduler's traffic counters rode the query above.
        assert dv["scheduler"]["index_traffic"].get("dv", 0) >= 1
        # Diagnostics aggregates include the tier group.
        info = s.diagnostics.gather()
        assert "tierHostBytes" in info
        assert "tierPromotions" in info
    finally:
        s.close()
