"""Byte-level parity with reference-generated artifacts.

The reference repo ships a real fragment file written by its Go roaring
implementation (testdata/sample_view/0, used by its fragment tests). Our
reader must parse it and our writer must produce a file the reader
round-trips identically — proving on-disk interchange compatibility.
"""

import os

import pytest

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.storage.bitmap import Bitmap

SAMPLE = "/root/reference/testdata/sample_view/0"

pytestmark = pytest.mark.skipif(
    not os.path.exists(SAMPLE), reason="reference testdata not mounted"
)


def test_parse_reference_fragment_file():
    with open(SAMPLE, "rb") as f:
        data = f.read()
    b = Bitmap.from_bytes(data)
    assert b.count() == 35001
    assert len(b.containers) == 14207
    vals = b.slice()
    assert int(vals[0]) == 32966
    assert all(vals[i] < vals[i + 1] for i in range(0, 200))


def test_roundtrip_reference_file():
    with open(SAMPLE, "rb") as f:
        b = Bitmap.from_bytes(f.read())
    b2 = Bitmap.from_bytes(b.to_bytes())
    assert b == b2
    assert b2.count() == 35001


def test_fragment_opens_reference_file(tmp_path):
    """A fragment pointed at the reference's file serves rows from it."""
    import shutil

    path = tmp_path / "0"
    shutil.copy(SAMPLE, path)
    f = Fragment(str(path), "i", "f", "standard", 0)
    f.open()
    total = sum(f.row_count(r) for r in f.rows())
    assert total == 35001
    assert f.rows()[0] == 0
    # Device plane of row 0 matches host storage.
    cols = f.row(0).columns()
    assert len(cols) == f.row_count(0)
    f.close()
