"""Device-plane fault tolerance (docs/fault-tolerance.md, device section).

Proves the degraded execution ladder end to end: dispatch failures are
classified (oom / compile / runtime / timeout), the per-signature and
plane-wide breakers route around the fused device path (per-shard XLA
walk, then full host/compressed-domain execution), HBM OOM gets
backpressure + retries instead of a client error, and half-open probes
re-close the breakers once faults clear — with dispatch counters as the
proof that serving actually returned to the device path.

The chaos test at the bottom is THE tier-1 combination proof: seed-pinned
device failpoints + tier demote churn + routing-epoch (cutover) churn,
asserting correct-or-clean-error during faults and full convergence
(breakers closed, device path re-promoted, zero host-ladder reads) after
they clear.
"""

import random

import numpy as np
import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.cluster.health import ResilienceConfig
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.parallel import EngineConfig
from pilosa_tpu.parallel.device_health import (
    CLOSED, COMPILE, DeviceDispatchError, DeviceDispatchTimeout,
    DevicePlaneHealth, HALF_OPEN, OOM, OPEN, RUNTIME, TIMEOUT,
    classify_device_error,
)
from pilosa_tpu.parallel.engine import Leaf, ShardedQueryEngine, _pop_elems
from pilosa_tpu.pql.parser import parse
from pilosa_tpu.tier import TierConfig

N_SHARDS = 2
SHARDS = tuple(range(N_SHARDS))


@pytest.fixture
def holder():
    h = Holder(None)
    h.open()
    idx = h.create_index("i")
    fld = idx.create_field("f")
    rng = np.random.default_rng(11)
    for row in range(6):
        for shard in SHARDS:
            cols = rng.choice(4096, size=60 + 13 * row, replace=False)
            for c in cols:
                fld.set_bit(row, shard * SHARD_WIDTH + int(c))
    yield h
    h.close()


def call(q):
    return parse(q).calls[0]


# ------------------------------------------------------ classification


class TestClassify:
    def test_oom_spellings(self):
        for msg in ("RESOURCE_EXHAUSTED: out of memory allocating",
                    "Out of memory while trying to allocate",
                    "injected HBM OOM at failpoint 'device-dispatch'"):
            assert classify_device_error(RuntimeError(msg)) == OOM

    def test_compile_spellings(self):
        for msg in ("INVALID_ARGUMENT: bad operand",
                    "Compilation failure: unsupported op",
                    "Mosaic lowering failed"):
            assert classify_device_error(RuntimeError(msg)) == COMPILE

    def test_timeout_by_type(self):
        assert classify_device_error(DeviceDispatchTimeout("x")) == TIMEOUT
        assert classify_device_error(TimeoutError()) == TIMEOUT
        from concurrent.futures import TimeoutError as FutTimeout

        assert classify_device_error(FutTimeout()) == TIMEOUT

    def test_generic_is_runtime(self):
        assert classify_device_error(RuntimeError("boom")) == RUNTIME


# ------------------------------------------------------ breaker lifecycle


class TestDevicePlaneHealth:
    def _dh(self, fake_clock, **kw):
        cfg = ResilienceConfig(**kw).validate()
        return DevicePlaneHealth(cfg, clock=fake_clock)

    def test_plane_opens_after_failures_and_probes_reclose(self, fake_clock):
        dh = self._dh(fake_clock, device_breaker_failures=3,
                      device_breaker_backoff=2.0)
        for _ in range(2):
            dh.record_failure(("a",), RUNTIME)
        assert dh.plane_state() == CLOSED and dh.plan() == "device"
        dh.record_failure(("a",), RUNTIME)
        assert dh.plane_state() == OPEN
        assert dh.plan() == "host"  # inside backoff: short circuit
        assert dh.snapshot()["plane_short_circuits"] == 1
        fake_clock.advance(2.0)
        assert dh.plan() == "device"  # THE half-open probe
        assert dh.plane_state() == HALF_OPEN
        assert dh.plan() == "host"  # probe in flight: others degrade
        dh.record_success(("a",))
        assert dh.plane_state() == CLOSED
        snap = dh.snapshot()
        assert snap["plane_opened"] == 1 and snap["plane_closed"] == 1

    def test_failed_probe_doubles_backoff(self, fake_clock):
        dh = self._dh(fake_clock, device_breaker_failures=1,
                      device_breaker_backoff=2.0,
                      device_breaker_backoff_max=5.0)
        dh.record_failure(None, RUNTIME)
        fake_clock.advance(2.0)
        assert dh.plan() == "device"
        dh.record_failure(None, RUNTIME)  # probe failed
        assert dh.plane_state() == OPEN
        fake_clock.advance(3.9)
        assert dh.plan() == "host"  # doubled to 4.0: not yet
        fake_clock.advance(0.1)
        assert dh.plan() == "device"
        dh.record_failure(None, RUNTIME)
        fake_clock.advance(4.9)  # capped at max 5.0
        assert dh.plan() == "host"
        fake_clock.advance(0.1)
        assert dh.plan() == "device"

    def test_sig_quarantine_routes_shard_only_that_sig(self, fake_clock):
        dh = self._dh(fake_clock, device_breaker_failures=100,
                      device_sig_failures=2, device_sig_backoff=10.0)
        bad, good = ("bad",), ("good",)
        dh.record_failure(bad, COMPILE)
        assert dh.plan(bad) == "device"
        dh.record_failure(bad, COMPILE)
        assert dh.plan(bad) == "shard"
        assert dh.plan(good) == "device"
        assert dh.plan() == "device"
        assert dh.sig_state(bad) == OPEN
        fake_clock.advance(10.0)
        assert dh.plan(bad) == "device"  # sig half-open probe
        dh.record_success(bad)
        assert dh.sig_state(bad) == CLOSED
        snap = dh.snapshot()
        assert snap["sig_quarantined"] == 1 and snap["sig_restored"] == 1

    def test_unresolved_probe_reclaims_after_backoff(self, fake_clock):
        # A probing query answered by the memo dispatches nothing; the
        # probe must re-claim after one base backoff, not wedge for
        # probe_ttl.
        dh = self._dh(fake_clock, device_breaker_failures=1,
                      device_breaker_backoff=2.0)
        dh.record_failure(None, RUNTIME)
        fake_clock.advance(2.0)
        assert dh.plan() == "device"  # claimed, never resolved
        fake_clock.advance(1.0)
        assert dh.plan() == "host"
        fake_clock.advance(1.0)
        assert dh.plan() == "device"  # re-claimed

    def test_quarantined_sig_never_serves_as_plane_probe(self, fake_clock):
        # A signature whose program deterministically fails must not be
        # the dispatch that probes an open plane while the sig's own
        # backoff is running: it would re-open a healthy plane on every
        # attempt. A healthy signature probes instead.
        dh = self._dh(fake_clock, device_breaker_failures=2,
                      device_sig_failures=1, device_breaker_backoff=2.0,
                      device_sig_backoff=10.0)
        bad = ("bad",)
        dh.record_failure(bad, COMPILE)
        dh.record_failure(bad, COMPILE)
        assert dh.plane_state() == OPEN and dh.sig_state(bad) == OPEN
        fake_clock.advance(2.0)  # plane backoff elapsed, sig's has not
        assert dh.plan(bad) == "host"  # bad sig routed down, no claim
        assert dh.plan(("good",)) == "device"  # a healthy sig probes
        dh.record_success(("good",))
        assert dh.plane_state() == CLOSED

    def test_single_sig_workload_still_recovers(self, fake_clock):
        # Liveness twin of the test above: when EVERY query shares the
        # quarantined signature, the sig becomes a legitimate JOINT probe
        # once its own backoff elapses — otherwise the plane could never
        # re-close under a single-shape workload.
        dh = self._dh(fake_clock, device_breaker_failures=2,
                      device_sig_failures=1, device_breaker_backoff=2.0,
                      device_sig_backoff=10.0)
        bad = ("only",)
        dh.record_failure(bad, RUNTIME)
        dh.record_failure(bad, RUNTIME)
        assert dh.plane_state() == OPEN
        fake_clock.advance(5.0)
        assert dh.plan(bad) == "host"  # sig backoff (10s) still running
        fake_clock.advance(5.0)
        assert dh.plan(bad) == "device"  # joint probe: both due
        dh.record_success(bad)
        assert dh.plane_state() == CLOSED
        assert dh.sig_state(bad) == CLOSED

    def test_lost_probe_expires_as_failure(self, fake_clock):
        dh = self._dh(fake_clock, device_breaker_failures=1,
                      device_breaker_backoff=2.0, probe_ttl=30.0)
        dh.record_failure(None, RUNTIME)
        fake_clock.advance(2.0)
        assert dh.plan() == "device"
        before = dh.snapshot()["plane_open_count"]
        fake_clock.advance(31.0)
        dh.plan()  # expiry noticed here
        assert dh.snapshot()["plane_open_count"] == before + 1

    def test_sig_backoff_honors_its_own_knob(self, fake_clock):
        # A sig backoff configured ABOVE the plane cap must not collapse
        # after a failed probe: each breaker doubles from (and is capped
        # no lower than) its OWN knob.
        dh = self._dh(fake_clock, device_breaker_failures=100,
                      device_sig_failures=1, device_breaker_backoff=2.0,
                      device_breaker_backoff_max=60.0,
                      device_sig_backoff=300.0)
        bad = ("bad",)
        dh.record_failure(bad, COMPILE)
        fake_clock.advance(299.9)
        assert dh.plan(bad) == "shard"  # 300s quarantine honored
        fake_clock.advance(0.1)
        assert dh.plan(bad) == "device"  # sig probe
        dh.record_failure(bad, COMPILE)  # probe fails: re-quarantined
        fake_clock.advance(299.9)
        # The next window is never SHORTER than the sig's own knob (the
        # bug was a collapse to the 60s plane cap on the first reopen).
        assert dh.plan(bad) == "shard"
        fake_clock.advance(0.2)
        assert dh.plan(bad) == "device"

    def test_counters_by_kind(self, fake_clock):
        dh = self._dh(fake_clock)
        dh.record_failure(None, OOM)
        dh.record_failure(None, COMPILE)
        dh.record_failure(None, TIMEOUT)
        snap = dh.snapshot()
        assert snap["failures_oom"] == 1
        assert snap["failures_compile"] == 1
        assert snap["failures_timeout"] == 1
        assert snap["dispatch_failures"] == 3

    def test_validate_rejects_bad_device_knobs(self):
        with pytest.raises(ValueError):
            ResilienceConfig(device_breaker_failures=0).validate()
        with pytest.raises(ValueError):
            ResilienceConfig(device_sig_backoff=0).validate()
        with pytest.raises(ValueError):
            ResilienceConfig(device_breaker_backoff=2.0,
                             device_breaker_backoff_max=1.0).validate()


# ------------------------------------------------------ failpoint action


class TestOomFailpoint:
    def test_oom_action_grammar_and_classification(self):
        try:
            failpoints.activate("device-dispatch=2*oom")
            assert failpoints.active()["device-dispatch"] == "2*oom"
            with pytest.raises(failpoints.InjectedFault) as ei:
                failpoints.fire("device-dispatch")
            assert classify_device_error(ei.value) == OOM
        finally:
            failpoints.reset()

    def test_oom_action_custom_message_still_classifies_oom(self):
        # A custom message must ride BEHIND the RESOURCE_EXHAUSTED prefix
        # — replacing it would silently turn an OOM-rung test into a
        # generic-failure test.
        try:
            failpoints.activate("device-dispatch=oom(hbm full)")
            with pytest.raises(failpoints.InjectedFault) as ei:
                failpoints.fire("device-dispatch")
            assert "hbm full" in str(ei.value)
            assert classify_device_error(ei.value) == OOM
        finally:
            failpoints.reset()


# ------------------------------------------------------ engine dispatch


class TestEngineFaults:
    def _engine(self, holder, **kw):
        tier = kw.pop("tier_config", TierConfig(host_bytes=1 << 26,
                                                prefetch_interval=0))
        return ShardedQueryEngine(holder, tier_config=tier, **kw)

    def test_dispatch_error_is_typed_and_recorded(self, holder):
        eng = self._engine(holder)
        try:
            failpoints.configure("device-dispatch", "error")
            with pytest.raises(DeviceDispatchError) as ei:
                eng.count("i", call("Count(Row(f=0))").children[0], SHARDS)
            assert ei.value.kind == RUNTIME
            assert eng.counters["device_dispatch_errors"] == 1
            assert eng.device_health.snapshot()["failures_runtime"] == 1
        finally:
            failpoints.reset()
            eng.close()

    def test_oom_backpressure_retry_never_errors(self, holder):
        eng = self._engine(holder)
        try:
            healthy = eng.count("i", call("Row(f=0)"), SHARDS)
            leaf_budget = eng.budgets["leaf_cache_bytes"]
            failpoints.configure("device-dispatch", "oom", count=1)
            got = eng.count("i", call("Row(f=1)"), SHARDS)
            assert got == eng.host_count("i", call("Row(f=1)"), SHARDS)
            assert eng.counters["oom_backpressure"] == 1
            assert eng.counters["oom_retries"] == 1
            assert eng.budgets["leaf_cache_bytes"] == max(
                leaf_budget // 2, 1 << 20)
            # The plane breaker saw a RECOVERED dispatch, not a failure.
            assert eng.device_health.plane_state() == CLOSED
            assert healthy == eng.count("i", call("Row(f=0)"), SHARDS)
        finally:
            failpoints.reset()
            eng.close()

    def test_oom_batch_splits_in_half(self, holder, monkeypatch):
        monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")  # memo off: the
        # batch must really dispatch, or the failpoint never fires
        eng = self._engine(holder)
        try:
            calls = [call(f"Row(f={r})") for r in range(4)]
            expect = [eng.host_count("i", c, SHARDS) for c in calls]
            # 2*oom: the full batch fails, the same-size retry fails, and
            # the two half-batches succeed (failpoint exhausted).
            failpoints.configure("device-dispatch", "oom", count=2)
            got = eng.count_batch("i", calls, SHARDS)
            assert [int(x) for x in got] == expect
            assert eng.counters["oom_batch_splits"] == 1
            assert eng.counters["oom_backpressure"] >= 1
        finally:
            failpoints.reset()
            eng.close()

    def test_watchdog_times_out_wedged_dispatch(self, holder):
        eng = self._engine(holder, config=EngineConfig(
            dispatch_watchdog=0.05, gather_workers=2))
        try:
            failpoints.configure("device-dispatch", "latency", arg=500)
            with pytest.raises(DeviceDispatchError) as ei:
                eng.count("i", call("Row(f=0)"), SHARDS)
            assert ei.value.kind == TIMEOUT
            assert eng.counters["watchdog_timeouts"] >= 1
            assert eng.device_health.snapshot()["failures_timeout"] >= 1
        finally:
            failpoints.reset()
            eng.close()

    def test_watchdog_inflight_bound_runs_inline(self, holder):
        # With every watchdog-pool slot occupied (parked on a wedged
        # runtime), further dispatches run INLINE instead of queueing —
        # a queued task's timeout would measure pool delay, not the
        # device, and the gather pool (the host ladder's lifeline) is a
        # separate pool entirely.
        eng = self._engine(holder, config=EngineConfig(
            dispatch_watchdog=0.05, gather_workers=2))
        try:
            failpoints.configure("device-dispatch", "latency", arg=150)
            with eng._lock:
                eng._watchdog_inflight = eng._WATCHDOG_WORKERS
            got = eng.count("i", call("Row(f=3)"), SHARDS)  # blocks ~150ms
            assert got == eng.host_count("i", call("Row(f=3)"), SHARDS)
            assert eng.counters["watchdog_timeouts"] == 0
            with eng._lock:  # undo the synthetic occupancy for teardown
                eng._watchdog_inflight = 0
        finally:
            failpoints.reset()
            eng.close()

    def test_watchdog_uses_dedicated_pool_not_gather_pool(self, holder):
        # A wedged dispatch must park a pilosa-dispatch worker, never a
        # pilosa-gather one: the host fallback ladder gathers on that
        # pool and would deadlock behind abandoned dispatches.
        eng = self._engine(holder, config=EngineConfig(
            dispatch_watchdog=0.05, gather_workers=2))
        try:
            failpoints.configure("device-dispatch", "latency", arg=200)
            with pytest.raises(DeviceDispatchError):
                eng.count("i", call("Row(f=2)"), SHARDS)
            assert eng._watchdog_pool is not None
            import threading as _threading

            assert any(t.name.startswith("pilosa-dispatch")
                       for t in _threading.enumerate())
            with eng._lock:
                assert eng._watchdog_inflight >= 1  # still parked
            failpoints.reset()
            # The host ladder still serves while the dispatch is parked.
            assert eng.host_count("i", call("Row(f=2)"), SHARDS) == \
                eng.host_count("i", call("Row(f=2)"), (0, 1))
            # The abandoned task drains once its injected latency AND its
            # first-touch jit compile finish — poll with a deadline (a
            # fixed sleep raced the compile on cold jit caches).
            import time as _t

            deadline = _t.monotonic() + 30.0
            while _t.monotonic() < deadline:
                with eng._lock:
                    if eng._watchdog_inflight == 0:
                        break
                _t.sleep(0.05)
            with eng._lock:
                assert eng._watchdog_inflight == 0
        finally:
            failpoints.reset()
            eng.close()

    def test_compile_failure_classified(self, holder):
        eng = self._engine(holder)
        try:
            failpoints.configure("device-compile", "error")
            with pytest.raises(DeviceDispatchError) as ei:
                eng.count("i", call("Row(f=2)"), SHARDS)
            assert ei.value.kind == COMPILE
            assert eng.device_health.snapshot()["failures_compile"] == 1
        finally:
            failpoints.reset()
            eng.close()

    def test_transfer_stage_failure_engages_breaker(self, holder,
                                                    monkeypatch):
        # A device that dies at the TRANSFER stage (device_put raising,
        # not the compiled call) must be classified + recorded like a
        # dispatch failure — otherwise the plane breaker stays closed and
        # every query 500s forever.
        import jax as _jax

        eng = self._engine(holder)

        def dead_tunnel(*a, **kw):
            raise RuntimeError("UNAVAILABLE: tunnel closed")

        try:
            monkeypatch.setattr(_jax, "device_put", dead_tunnel)
            with pytest.raises(DeviceDispatchError) as ei:
                eng.count("i", call("Row(f=0)"), SHARDS)
            assert ei.value.kind == RUNTIME
            assert eng.device_health.snapshot()["dispatch_failures"] == 1
        finally:
            eng.close()

    def test_host_count_bit_exact_vs_device(self, holder):
        eng = self._engine(holder)
        try:
            for q in ("Row(f=0)",
                      "Intersect(Row(f=0), Row(f=1))",
                      "Union(Row(f=0), Row(f=1), Row(f=2))",
                      "Difference(Row(f=3), Row(f=1))",
                      "Xor(Row(f=2), Row(f=4))"):
                dev = eng.count("i", call(q), SHARDS)
                host = eng.host_count("i", call(q), (0, 1))
                assert dev == host, q
        finally:
            eng.close()

    def test_host_count_reads_demoted_tier_bytes(self, holder, monkeypatch):
        monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")
        eng = self._engine(holder)
        try:
            healthy = eng.count("i", call("Row(f=0)"), SHARDS)
            key = ("i", Leaf("f", "standard", 0), SHARDS)
            eng.tier.demote(key)
            assert eng.tier.drain()
            base = eng.tier.snapshot()["promotions_host"]
            assert eng.host_count("i", call("Row(f=0)"), SHARDS) == healthy
            assert eng.tier.snapshot()["promotions_host"] == base + 1
            assert eng.counters["host_counts"] == 1
        finally:
            eng.close()

    def test_host_topn_matches_device(self, holder):
        eng = self._engine(holder)
        try:
            src = call("Row(f=0)")
            ids = [1, 2, 3, 4]
            d_rc, d_inter, d_src = eng.topn_shard_counts(
                "i", "f", ids, SHARDS, src, need_row_counts=True)
            h_rc, h_inter, h_src = eng.host_topn_shard_counts(
                "i", "f", ids, SHARDS, src, need_row_counts=True)
            assert np.array_equal(np.asarray(d_rc), np.asarray(h_rc))
            assert np.array_equal(np.asarray(d_inter), np.asarray(h_inter))
            assert np.array_equal(np.asarray(d_src), np.asarray(h_src))
        finally:
            eng.close()

    def test_pop_elems_matches_python_popcount(self):
        rng = np.random.default_rng(5)
        arr = rng.integers(0, 2**32, size=(3, 64), dtype=np.uint32)
        want = sum(bin(int(x)).count("1") for x in arr.flat)
        assert int(_pop_elems(arr).sum()) == want


# ------------------------------------------------- compressed-domain cold


class TestColdHostCount:
    def test_cold_count_skips_device_then_promotes_on_repeat(
            self, holder, monkeypatch):
        monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")
        eng = ShardedQueryEngine(
            holder, tier_config=TierConfig(host_bytes=1 << 26,
                                           prefetch_interval=0))
        try:
            healthy = eng.count("i", call("Row(f=5)"), SHARDS)
            dispatches = eng.counters["count_dispatches"]
            # Evict + demote the plane, then drop the device entry.
            key = ("i", Leaf("f", "standard", 5), SHARDS)
            eng.tier.demote(key)
            assert eng.tier.drain()
            with eng._lock:
                ent = eng._leaf_cache.pop(key, None)
                if ent is not None:
                    eng._leaf_bytes -= ent[1].nbytes
            # First touch: answered compressed-domain, no dispatch.
            got = eng.count("i", call("Row(f=5)"), SHARDS)
            assert got == healthy
            assert eng.counters["host_cold_counts"] == 1
            assert eng.counters["count_dispatches"] == dispatches
            # Second touch: promotes through the tier onto the device.
            tier_hits = eng.counters["leaf_tier_hits"]
            got = eng.count("i", call("Row(f=5)"), SHARDS)
            assert got == healthy
            assert eng.counters["leaf_tier_hits"] == tier_hits + 1
            assert eng.counters["count_dispatches"] == dispatches + 1
        finally:
            eng.close()

    def test_disabled_by_knob(self, holder, monkeypatch):
        monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")
        eng = ShardedQueryEngine(
            holder, config=EngineConfig(cold_host_count=0),
            tier_config=TierConfig(host_bytes=1 << 26, prefetch_interval=0))
        try:
            key = ("i", Leaf("f", "standard", 4), SHARDS)
            eng.tier.demote(key)
            assert eng.tier.drain()
            eng.count("i", call("Row(f=4)"), SHARDS)
            assert eng.counters["host_cold_counts"] == 0
        finally:
            eng.close()


# ------------------------------------------------------ executor ladder


class TestExecutorLadder:
    def _executor(self, holder, **resilience):
        ex = Executor(holder)
        if resilience:
            ex.cluster.health.configure(
                ResilienceConfig(**resilience).validate())
        return ex

    def test_count_served_by_host_ladder_under_fault(self, holder):
        ex = self._executor(holder)
        try:
            healthy = ex.execute("i", "Count(Intersect(Row(f=1),Row(f=2)))")[0]
            failpoints.configure("device-dispatch", "error")
            # A commutative respelling now canonicalizes onto the same
            # memo entry (docs/query-compiler.md) and must still answer.
            got = ex.execute("i", "Count(Intersect(Row(f=2),Row(f=1)))")[0]
            # A fresh leaf SET busts the memo, so THIS query exercises
            # the faulted dispatch + host-ladder value path.
            fresh = ex.execute("i", "Count(Intersect(Row(f=0),Row(f=1)))")[0]
            healthy2 = ex.execute("i", "Count(Intersect(Row(f=1),Row(f=2)))")[0]
            assert got == healthy == healthy2
            failpoints.reset()
            # Value-check the ladder-served answer against the healthy
            # DEVICE path for the same query — a set+clear bumps the
            # generation so the re-execution cannot be a memo read of
            # the host ladder's own stored value.
            fld = holder.index("i").field("f")
            fld.set_bit(0, 8000)
            fld.clear_bit(0, 8000)
            assert fresh == ex.execute(
                "i", "Count(Intersect(Row(f=0),Row(f=1)))")[0]
            assert ex.engine.counters["host_counts"] >= 1
        finally:
            failpoints.reset()
            ex.close()

    def test_plane_opens_then_host_routed_then_recloses(self, holder):
        ex = self._executor(holder, device_breaker_failures=2,
                            device_breaker_backoff=1.0)
        try:
            queries = [f"Count(Union(Row(f=0),Row(f={r})))" for r in
                       (1, 2, 3, 4)]
            expect = [ex.execute("i", q)[0] for q in queries]
            failpoints.configure("device-dispatch", "error")
            dh = ex.engine.device_health
            # A fresh bit (cols were drawn < 4096) busts every memo AND
            # shifts each Union count by exactly one, so the degraded
            # answers are checkable against the healthy baseline.
            fld = holder.index("i").field("f")
            fld.set_bit(0, 8000)
            got = [ex.execute("i", q)[0] for q in queries]
            assert got == [e + 1 for e in expect]
            fld.clear_bit(0, 8000)
            assert [ex.execute("i", q)[0] for q in queries] == expect
            assert dh.plane_state() == OPEN
            assert ex.engine.counters["host_counts"] >= 2
            # Heal: faults cleared + backoff elapsed -> the next fresh
            # query IS the half-open probe and re-closes the plane.
            failpoints.reset()
            import time as _t

            dh.clock = (lambda base=_t.monotonic: base() + 60.0)
            dispatches = ex.engine.counters["count_dispatches"]
            got = ex.execute("i", "Count(Xor(Row(f=0),Row(f=5)))")[0]
            assert got == ex.engine.host_count(
                "i", call("Xor(Row(f=0),Row(f=5))"), SHARDS)
            assert dh.plane_state() == CLOSED
            assert ex.engine.counters["count_dispatches"] == dispatches + 1
        finally:
            failpoints.reset()
            ex.close()

    def test_sig_quarantine_leaves_other_sigs_on_device(self, holder):
        ex = self._executor(holder, device_breaker_failures=100,
                            device_sig_failures=1)
        try:
            bad = "Count(Difference(Row(f=0),Row(f=2)))"
            good = "Count(Union(Row(f=3),Row(f=4)))"
            expect_bad = ex.engine.host_count(
                "i", call("Difference(Row(f=0),Row(f=2))"), SHARDS)
            # host_count stored the memo: bust it so the query dispatches.
            holder.index("i").field("f").set_bit(0, 8001)
            holder.index("i").field("f").clear_bit(0, 8001)
            failpoints.configure("device-dispatch", "error", count=1)
            assert ex.execute("i", bad)[0] == expect_bad  # in-flight rung
            # The signature is now quarantined: served correctly WITHOUT
            # the engine (failpoint exhausted — a dispatch would succeed,
            # so an unchanged dispatch counter proves the routing).
            dispatches = ex.engine.counters["count_dispatches"]
            holder.index("i").field("f").set_bit(0, 8002)
            holder.index("i").field("f").clear_bit(0, 8002)  # memo-bust
            assert ex.execute("i", bad)[0] == expect_bad
            assert ex.engine.counters["count_dispatches"] == dispatches
            # A different signature still rides the device.
            ex.execute("i", good)
            assert ex.engine.counters["count_dispatches"] == dispatches + 1
        finally:
            failpoints.reset()
            ex.close()

    def test_topn_correct_under_device_fault(self, holder):
        ex = self._executor(holder)
        try:
            q = "TopN(f, Row(f=0), n=3)"
            healthy = ex.execute("i", q)[0]
            failpoints.configure("device-dispatch", "error")
            # Bump generations so the aux memo can't answer the repeat
            # (set+clear leaves the data identical).
            holder.index("i").field("f").set_bit(0, 4500)
            holder.index("i").field("f").clear_bit(0, 4500)
            degraded = ex.execute("i", q)[0]
            assert [(p.id, p.count) for p in degraded] == \
                [(p.id, p.count) for p in healthy]
            assert ex.engine.counters["host_topn"] >= 1
        finally:
            failpoints.reset()
            ex.close()

    def test_topn_with_bsi_src_takes_per_shard_rung(self, holder):
        # A BSI Range src compiles onto the fused path but has NO host
        # twin: with the device faulted, TopN must drop to the per-shard
        # walk (rung 1), never surface the dispatch error.
        from pilosa_tpu.core.field import FieldOptions

        idx = holder.index("i")
        idx.create_field_if_not_exists(
            "v", FieldOptions(type="int", min=0, max=100))
        fld = idx.field("v")
        for col in range(0, 200, 3):
            fld.set_value(col, col % 70)
        q = "TopN(f, Range(v > 10), n=3)"
        ex = self._executor(holder)
        try:
            healthy = ex.execute("i", q)[0]
            assert healthy  # the filter actually selects rows
            holder.index("i").field("f").set_bit(0, 8003)
            holder.index("i").field("f").clear_bit(0, 8003)  # memo-bust
            failpoints.configure("device-dispatch", "error")
            degraded = ex.execute("i", q)[0]
            assert [(p.id, p.count) for p in degraded] == \
                [(p.id, p.count) for p in healthy]
        finally:
            failpoints.reset()
            ex.close()

    def test_bsi_short_circuits_to_per_shard_when_plane_open(self, holder):
        # BSI has no host twin, so its whole degraded ladder is the
        # per-shard walk — and with the plane breaker OPEN, it must be
        # taken BEFORE any dispatch (no failing dispatch, no watchdog
        # stall per query on a known-sick device).
        from pilosa_tpu.core.field import FieldOptions

        idx = holder.index("i")
        idx.create_field_if_not_exists(
            "w", FieldOptions(type="int", min=0, max=50))
        fld = idx.field("w")
        for col in range(0, 60, 2):
            fld.set_value(col, col % 40)
        ex = self._executor(holder, device_breaker_failures=1)
        try:
            healthy = ex.execute("i", "Sum(field=w)")[0].to_dict()
            failpoints.configure("device-dispatch", "error")
            fld.set_value(1, 5)  # busts the aux memo (and shifts the sum)
            want = {"value": healthy["value"] + 5,
                    "count": healthy["count"] + 1}
            degraded = ex.execute("i", "Sum(field=w)")[0].to_dict()
            assert degraded == want  # mid-request rung
            assert ex.engine.device_health.plane_state() == OPEN
            failures = ex.engine.device_health.snapshot()[
                "dispatch_failures"]
            # Plane open: the NEXT Sum never dispatches at all.
            fld.set_value(3, 5)
            want = {"value": want["value"] + 5, "count": want["count"] + 1}
            assert ex.execute("i", "Sum(field=w)")[0].to_dict() == want
            assert ex.engine.device_health.snapshot()[
                "dispatch_failures"] == failures
        finally:
            failpoints.reset()
            ex.close()

    def test_bitmap_falls_back_per_shard(self, holder):
        ex = self._executor(holder)
        try:
            q = "Intersect(Row(f=0), Row(f=1))"
            healthy = ex.execute("i", q)[0]
            failpoints.configure("device-dispatch", "error")
            degraded = ex.execute("i", q)[0]
            assert degraded.count() == healthy.count()
        finally:
            failpoints.reset()
            ex.close()


# --------------------------------------------- deadline between chunks


class TestDeadlineBetweenChunks:
    def test_multichunk_topn_503s_midflight(self, holder, monkeypatch):
        from pilosa_tpu.executor import ExecOptions
        from pilosa_tpu.sched.deadline import (Deadline,
                                               DeadlineExceededError)

        # Force one candidate row per device chunk.
        monkeypatch.setenv("PILOSA_TOPN_CHUNK_BYTES", "1")
        ex = Executor(holder)
        ticks = {"n": 0}

        def clock():
            ticks["n"] += 1
            return float(ticks["n"])

        try:
            opt = ExecOptions(deadline=Deadline(10.0, clock=clock))
            with pytest.raises(DeadlineExceededError):
                ex.execute("i", "TopN(f, Row(f=0), n=5)",
                           shards=list(SHARDS), opt=opt)
        finally:
            ex.close()

    def test_phase_boundary_check_counts(self, holder):
        from pilosa_tpu.executor import ExecOptions
        from pilosa_tpu.sched.deadline import (Deadline,
                                               DeadlineExceededError)
        from pilosa_tpu.stats import new_stats_client

        holder.stats = new_stats_client("inmem", "")
        ex = Executor(holder)
        clock = {"now": 0.0}

        def tick():
            return clock["now"]

        try:
            opt = ExecOptions(deadline=Deadline(5.0, clock=tick))
            # Expire the budget before execution starts the second phase:
            # the phase-2 gate must 503 and count.
            orig = ex._execute_topn_shards

            def expiring(index, c, shards, o):
                out = orig(index, c, shards, o)
                clock["now"] = 100.0
                return out

            ex._execute_topn_shards = expiring
            with pytest.raises(DeadlineExceededError):
                ex.execute("i", "TopN(f, n=3)", shards=list(SHARDS), opt=opt)
            assert holder.stats.snapshot()["counters"].get(
                "DeadlineMidQuery", 0) >= 1
        finally:
            ex.close()


# ------------------------------------------------------------ chaos combo


pytestmark_chaos = pytest.mark.chaos


@pytest.mark.chaos
def test_device_chaos_with_tier_churn_and_cutover(holder, fake_clock):
    """THE combination proof (tier-1, seed-pinned, fake breaker clock):
    device failpoints (error/oom/compile) toggle per round while planes
    churn through the tier (demote + drain every round) and routing
    epochs advance mid-round via rebalance begin/cutover/commit on the
    executor's own cluster (single node: placement never changes, the
    epoch re-read gates still fire). Every query must be CORRECT — the
    ladder never surfaces a device fault — and after faults clear the
    breakers re-close, serving returns to the device path, and a final
    round runs with zero host-ladder reads."""
    seed = 1234
    rng = random.Random(seed)
    ex = Executor(holder)
    ex.cluster.health.configure(ResilienceConfig(
        device_breaker_failures=2, device_breaker_backoff=1.0,
        device_sig_failures=2).validate())
    eng = ex.engine
    eng.device_health.clock = fake_clock
    queries = [
        "Count(Row(f=0))",
        "Count(Intersect(Row(f=0),Row(f=1)))",
        "Count(Union(Row(f=1),Row(f=2),Row(f=3)))",
        "Count(Difference(Row(f=4),Row(f=0)))",
        "Count(Xor(Row(f=2),Row(f=5)))",
    ]
    expect = [ex.execute("i", q)[0] for q in queries]
    fld = holder.index("i").field("f")
    node = ex.cluster.node
    try:
        for rnd in range(8):
            # Fault schedule for this round (seed-pinned).
            failpoints.reset()
            action = rng.choice(["none", "error", "oom", "compile", "error"])
            if action == "error":
                failpoints.configure("device-dispatch", "error",
                                     count=rng.randint(1, 3))
            elif action == "oom":
                failpoints.configure("device-dispatch", "oom",
                                     count=rng.randint(1, 2))
            elif action == "compile":
                failpoints.configure("device-compile", "error",
                                     count=rng.randint(1, 2))
            # Tier churn: demote a couple of planes and settle the worker.
            for row in rng.sample(range(6), 2):
                eng.tier.demote(("i", Leaf("f", "standard", row), SHARDS))
            eng.tier.drain()
            # Cutover churn: advance the routing epoch mid-round.
            ex.cluster.begin_rebalance([node])
            ex.cluster.apply_cutover("i", rng.randrange(N_SHARDS))
            # A tiny write pair busts memos so queries really execute.
            col = 4097 + rnd
            fld.set_bit(0, col)
            fld.clear_bit(0, col)
            for q, want in zip(queries, expect):
                got = ex.execute("i", q)[0]  # correct, never a 500
                assert got == want, (rnd, action, q)
            ex.cluster.commit_topology([node])
            fake_clock.advance(rng.choice([0.2, 1.1, 2.5]))
        # Faults clear; breakers converge through half-open probes.
        failpoints.reset()
        for _ in range(6):
            fake_clock.advance(2.0)
            fld.set_bit(0, 5000)
            fld.clear_bit(0, 5000)
            for q, want in zip(queries, expect):
                assert ex.execute("i", q)[0] == want
            if eng.device_health.plane_state() == CLOSED:
                break
        assert eng.device_health.plane_state() == CLOSED
        # Fully converged: a fresh round serves from the device with ZERO
        # host-ladder reads and climbing dispatch counters.
        host_before = eng.counters["host_counts"] + eng.counters["host_topn"]
        dispatches = eng.counters["count_dispatches"]
        fld.set_bit(0, 5001)
        fld.clear_bit(0, 5001)
        for q, want in zip(queries, expect):
            assert ex.execute("i", q)[0] == want
        assert eng.counters["host_counts"] + eng.counters["host_topn"] \
            == host_before
        assert eng.counters["count_dispatches"] > dispatches
    finally:
        failpoints.reset()
        ex.close()
