"""Tenant QoS tests: trace-charged budgets, SLO-classed shedding.

Ledger math runs on the fake monotonic clock from conftest (refill only
moves when the test advances time), scheduler integration uses real
threads parked on the admission queues, and the HTTP tests drive the
X-Pilosa-Tenant header end to end through a live server.
"""

import json
import random
import threading

import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.obs.trace import Trace
from pilosa_tpu.sched import (
    CLASS_BATCH,
    CLASS_INTERACTIVE,
    Deadline,
    QosConfig,
    QueryScheduler,
    QueueFullError,
    SchedulerConfig,
    TenantBudgetError,
    TenantLedger,
)
from pilosa_tpu.sched.qos import measured_cost_ms


def ledger(fake_clock, **kw):
    kw.setdefault("rate", 10.0)       # 10 ms of budget per second
    kw.setdefault("burst", 100.0)
    kw.setdefault("estimate_ms", 50.0)
    return TenantLedger(QosConfig(**kw), clock=fake_clock,
                        rng=random.Random(7))


# ------------------------------------------------------------------ config


def test_qos_config_validation():
    QosConfig().validate()  # defaults are legal (and disabled: rate 0)
    for bad in (
        QosConfig(rate=-1),
        QosConfig(burst=0),
        QosConfig(default_tenant_share=0),
        QosConfig(interactive_cap=0.5),
        QosConfig(estimate_ms=-1),
    ):
        with pytest.raises(ValueError):
            bad.validate()


def test_disabled_ledger_is_free(fake_clock):
    led = ledger(fake_clock, rate=0.0)
    assert not led.enabled
    assert led.admission_verdict("t", CLASS_BATCH) is False
    assert led.charge_estimate("t") == 0.0
    led.settle("t", 0.0, 123.0)  # no-op, no bucket created
    assert led.snapshot()["tenants"] == 0
    assert led.snapshot()["enabled"] is False


# ----------------------------------------------------------------- buckets


def test_refill_and_burst_cap(fake_clock):
    led = ledger(fake_clock)
    # A new bucket starts full at burst x share.
    assert led.balance("t") == pytest.approx(100.0)
    led.charge_estimate("t")
    assert led.balance("t") == pytest.approx(50.0)
    # Refill at rate x share ms per second of wall time...
    fake_clock.advance(2.0)
    assert led.balance("t") == pytest.approx(70.0)
    # ...capped at burst x share, no matter how long the idle.
    fake_clock.advance(3600.0)
    assert led.balance("t") == pytest.approx(100.0)


def test_share_scales_rate_and_cap(fake_clock):
    led = ledger(fake_clock)
    led.set_share("gold", 2.0)
    for _ in range(4):
        led.charge_estimate("gold")  # 200 charged
    assert led.balance("gold") == pytest.approx(-100.0)
    fake_clock.advance(5.0)  # refills 10 * 2.0 * 5 = 100
    assert led.balance("gold") == pytest.approx(0.0)
    fake_clock.advance(3600.0)
    assert led.balance("gold") == pytest.approx(200.0)  # burst x share
    with pytest.raises(ValueError):
        led.set_share("gold", 0.0)


# ------------------------------------------------------------ shed ordering


def test_batch_sheds_at_dry_with_derived_retry_after(fake_clock):
    led = ledger(fake_clock)
    for _ in range(3):
        led.charge_estimate("noisy")  # balance 100 - 150 = -50
    with pytest.raises(TenantBudgetError) as ei:
        led.admission_verdict("noisy", CLASS_BATCH)
    # Typed 429: the tenant rides the error so a multiplexing client can
    # throttle one stream, and Retry-After is derived from THIS tenant's
    # deficit: (debt + estimate) / rate = (50 + 50) / 10 = 10s, +/-25%.
    assert ei.value.tenant == "noisy"
    assert 10.0 * 0.75 <= ei.value.retry_after <= 10.0 * 1.25
    assert led.counters["shed_batch"] == 1
    # Other tenants are untouched: fresh bucket, no shed.
    assert led.admission_verdict("quiet", CLASS_BATCH) is False


def test_interactive_defers_until_hard_cap(fake_clock):
    led = ledger(fake_clock, interactive_cap=2.0)  # cap: 200ms of debt
    for _ in range(3):
        led.charge_estimate("t")  # balance -50: dry but under the cap
    assert led.admission_verdict("t", CLASS_INTERACTIVE) is True
    assert led.counters["deferred"] == 1
    for _ in range(4):
        led.charge_estimate("t")  # balance -250: past 2.0 x 100 debt
    with pytest.raises(TenantBudgetError):
        led.admission_verdict("t", CLASS_INTERACTIVE)
    assert led.counters["shed_interactive"] == 1
    # Batch for the same tenant shed the whole time.
    with pytest.raises(TenantBudgetError):
        led.admission_verdict("t", CLASS_BATCH)


def test_retry_after_clamped(fake_clock):
    # A huge deficit must not advertise a wait past RETRY_MAX...
    led = ledger(fake_clock, rate=0.001)
    for _ in range(10):
        led.charge_estimate("t")
    with pytest.raises(TenantBudgetError) as ei:
        led.admission_verdict("t", CLASS_BATCH)
    assert ei.value.retry_after == TenantLedger.RETRY_MAX
    # ...and a tiny one never says "0" (stampede).
    led2 = ledger(fake_clock, rate=1e9)
    led2.charge_estimate("t")
    led2._buckets["t"].balance = -1e-9
    with pytest.raises(TenantBudgetError) as ei:
        led2.admission_verdict("t", CLASS_BATCH)
    assert ei.value.retry_after >= TenantLedger.RETRY_MIN


# ---------------------------------------------------------------- charging


def test_settle_reconciles_estimate_to_measured(fake_clock):
    led = ledger(fake_clock)
    est = led.charge_estimate("t")
    assert est == 50.0
    led.settle("t", est, measured=200.0)
    # Net charge is the MEASURED cost: 100 - 200.
    assert led.balance("t") == pytest.approx(-100.0)
    assert led.counters["settled_traced"] == 1
    # First sample seeds the EWMA; the second folds in at 0.1.
    snap = led.snapshot()
    assert snap["top"]["t"]["mean_ms"] == pytest.approx(200.0)
    led.settle("t", led.charge_estimate("t"), measured=100.0)
    assert led.snapshot()["top"]["t"]["mean_ms"] == pytest.approx(190.0)


def test_untraced_query_charged_rolling_mean(fake_clock):
    led = ledger(fake_clock)
    # No samples yet: an untraced settle stands on the estimate.
    led.settle("t", led.charge_estimate("t"), measured=None)
    assert led.balance("t") == pytest.approx(50.0)
    assert led.counters["settled_untraced"] == 1
    # With a traced mean established, untraced queries charge the mean —
    # a low sample rate cannot starve the ledger.
    led.settle("t", led.charge_estimate("t"), measured=30.0)  # 50-30 = 20
    led.settle("t", led.charge_estimate("t"), measured=None)  # 20-30 = -10
    assert led.balance("t") == pytest.approx(-10.0)


def test_measured_cost_sums_charged_spans_only(fake_clock):
    t = Trace("00ff", clock=fake_clock)
    t.record("device.dispatch", 5.0)
    t.record("gather", 3.0)
    t.record("tier.promote", 2.0)
    t.record("sched.wait", 400.0)  # queueing is the penalty, not the crime
    t.record("parse", 1.0)
    assert measured_cost_ms(t) == pytest.approx(10.0)
    # No active trace and no argument -> None (caller uses the mean).
    assert measured_cost_ms() is None


# ----------------------------------------------------------------- bounds


def test_tenant_table_recency_eviction(fake_clock):
    led = ledger(fake_clock)
    led.TENANTS_MAX = 3  # instance override; class default is 1024
    for t in ("a", "b", "c"):
        led.charge_estimate(t)
    led.charge_estimate("a")  # refresh a: b is now least recent
    led.charge_estimate("d")  # evicts b
    snap = led.snapshot()
    assert snap["tenants"] == 3
    assert led.counters["tenants_evicted"] == 1
    assert "b" not in snap["top"] and "a" in snap["top"]
    # An evicted tenant only forgot history: it comes back with a full
    # bucket, never an error.
    assert led.balance("b") == pytest.approx(100.0)


def test_snapshot_bounded_top_n(fake_clock):
    led = ledger(fake_clock)
    for i in range(10):
        for _ in range(i + 1):
            led.settle(f"t{i}", 0.0, measured=10.0)
    snap = led.snapshot(top_n=3)
    assert snap["tenants"] == 10
    assert len(snap["top"]) == 3
    # Ranked by cumulative charged cost: the busiest three.
    assert set(snap["top"]) == {"t9", "t8", "t7"}


# ------------------------------------------------- scheduler integration


def test_scheduler_sheds_dry_tenant(fake_clock):
    led = ledger(fake_clock, estimate_ms=60.0)
    sched = QueryScheduler(SchedulerConfig(), qos=led)
    with sched.admit(CLASS_BATCH, tenant="noisy"):
        pass  # charges 60, settles at the estimate (untraced, no mean)
    with sched.admit(CLASS_BATCH, tenant="noisy"):
        pass  # balance now -20: dry
    with pytest.raises(TenantBudgetError) as ei:
        with sched.admit(CLASS_BATCH, tenant="noisy"):
            pass  # pragma: no cover - shed before entry
    assert ei.value.tenant == "noisy"
    assert sched.counters["shed_tenant"] == 1
    # A shed costs nothing: no slot taken, no admitted tick.
    assert sched.counters["admitted_batch"] == 2
    # The quiet tenant is unaffected by the noisy one's debt.
    with sched.admit(CLASS_BATCH, tenant="quiet"):
        pass
    assert sched.counters["admitted_batch"] == 3


def test_over_budget_waiter_yields_to_in_budget(fake_clock):
    """The shed ordering contract's queue half: a released slot goes to
    the in-budget queue head even when an over-budget waiter has been
    parked longer."""
    led = ledger(fake_clock, interactive_cap=100.0)
    led.charge_estimate("noisy")
    led.charge_estimate("noisy")  # balance 0: over budget, defers
    sched = QueryScheduler(
        SchedulerConfig(interactive_concurrency=1, max_queue=8), qos=led)
    order = []
    hold, entered = threading.Event(), threading.Event()

    def occupant():
        with sched.admit(CLASS_INTERACTIVE, tenant="quiet"):
            entered.set()
            hold.wait(timeout=10)

    def runner(tenant):
        with sched.admit(CLASS_INTERACTIVE, tenant=tenant):
            order.append(tenant)

    t0 = threading.Thread(target=occupant)
    t0.start()
    assert entered.wait(timeout=5)
    t_noisy = threading.Thread(target=runner, args=("noisy",))
    t_noisy.start()
    assert wait_until(lambda: sched.queue_depth() == 1)
    t_quiet = threading.Thread(target=runner, args=("quiet",))
    t_quiet.start()
    assert wait_until(lambda: sched.queue_depth() == 2)
    assert sched.counters["deferred_over_budget"] == 1
    hold.set()
    for t in (t0, t_noisy, t_quiet):
        t.join(timeout=10)
    # The quiet (in-budget) tenant admitted first despite arriving last.
    assert order == ["quiet", "noisy"]


def wait_until(cond, timeout=5.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.002)
    return False


def test_qos_charge_failpoint_does_not_leak_slot(fake_clock):
    """Settle happens AFTER the slot release: a qos-charge fault
    surfaces to the caller but never wedges the concurrency gate."""
    led = ledger(fake_clock)
    sched = QueryScheduler(
        SchedulerConfig(interactive_concurrency=1), qos=led)
    failpoints.configure("qos-charge", "error", count=1,
                         message="injected settle fault")
    try:
        with pytest.raises(failpoints.InjectedFault,
                           match="injected settle fault"):
            with sched.admit(CLASS_INTERACTIVE, tenant="t"):
                pass
        # The slot came back: this admit must not park (a leaked slot
        # would park it until the deadline trips).
        with sched.admit(CLASS_INTERACTIVE, tenant="t",
                         deadline=Deadline(2.0)):
            pass
        assert sched.counters["admitted"] == 2
    finally:
        failpoints.reset()


# --------------------------------------------------------------- HTTP e2e


@pytest.fixture
def qos_server(tmp_path):
    from pilosa_tpu.server.server import Server

    s = Server(
        data_dir=str(tmp_path / "node0"), cache_flush_interval=0,
        qos_config=QosConfig(rate=0.001, burst=5.0, interactive_cap=2.0,
                             estimate_ms=5.0),
    )
    s.open()
    yield s
    s.close()


def _post(port, path, body, headers=None):
    import http.client

    conn = http.client.HTTPConnection(f"localhost:{port}", timeout=30)
    try:
        conn.request("POST", path, body=body.encode(),
                     headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def test_http_tenant_header_end_to_end(qos_server):
    from pilosa_tpu.server.client import InternalClient

    s = qos_server
    client = InternalClient()
    host = f"localhost:{s.port}"
    client.create_index(host, "i")
    client.create_field(host, "i", "f")
    client.query(host, "i", "Set(1, f=1)")

    # Explicit tenant header: query admits, bucket charged, trace tagged.
    status, _, body = _post(s.port, "/index/i/query", "Count(Row(f=1))",
                            {"X-Pilosa-Tenant": "acme"})
    assert status == 200
    assert json.loads(body)["results"][0] == 1
    snap = s.qos.snapshot()
    assert "acme" in snap["top"] and snap["top"]["acme"]["queries"] == 1
    traces = [t for t in s.trace_recorder.traces()
              if t.get("tags", {}).get("tenant") == "acme"]
    assert traces, "traced query must carry the tenant tag"
    # ...and the ledger billed it as a qos.charge span.
    assert any(sp["name"] == "qos.charge" for sp in traces[0]["spans"])

    # Shed ordering over HTTP. Default tenant is the index name: drain
    # "i" to dry-but-under-the-hard-cap by hand (2 x 5ms > burst-less
    # refill at rate 0.001).
    s.qos.charge_estimate("i")
    s.qos.charge_estimate("i")
    assert s.qos.balance("i") <= 0
    # Interactive still admits (deferred, not shed)...
    status, _, body = _post(s.port, "/index/i/query", "Count(Row(f=1))")
    assert status == 200
    # ...but batch (an import) sheds with the typed 429.
    payload = json.dumps({"shard": 0, "rowIDs": [2], "columnIDs": [9]})
    status, headers, body = _post(
        s.port, "/index/i/field/f/import", payload,
        {"Content-Type": "application/json"})
    assert status == 429
    assert headers.get("X-Pilosa-Tenant") == "i"
    assert float(headers.get("Retry-After")) >= 1
    # Past the hard cap (2.0 x 5.0 = 10ms of debt), interactive sheds too.
    for _ in range(4):
        s.qos.charge_estimate("i")
    status, headers, _ = _post(s.port, "/index/i/query", "Count(Row(f=1))")
    assert status == 429
    assert headers.get("X-Pilosa-Tenant") == "i"
    snap = s.qos.snapshot()
    assert snap["shed_batch"] >= 1 and snap["shed_interactive"] >= 1

    # The ledger is a /debug/vars group (docs/observability.md).
    import urllib.request

    with urllib.request.urlopen(f"http://{host}/debug/vars") as resp:
        dv = json.load(resp)
    assert dv["qos"]["enabled"] is True
    assert dv["qos"]["shed_batch"] >= 1
    assert "autoscale" in dv  # controller group rides along, even idle
