"""Shared lint plumbing: violations, annotation grammar, file context.

The annotation grammar is deliberately rigid so it can be parsed with one
regex and audited by grep:

    # pilint: allow-<kind>(<reason>)

`kind` is one of the KNOWN_KINDS below and `reason` is mandatory prose
(>= 4 characters — "wip" does not explain anything to the next reader).
An annotation applies to the line it sits on and to the line directly
below it (so it can ride above a statement too long to share a line).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# One kind per rule that supports suppression. R2 (jax-free zones) has no
# escape hatch on purpose: a jax import in a config module is never
# acceptable — move the import into the function that needs it.
# "failpoint" is shared by both halves of R6: on a fire() site it excuses
# a name kept out of the docs table, and in a TEST file it marks a
# deliberately-bogus spec (registry/grammar tests) as not-a-typo.
# "span" mirrors it for R7: on a recording site it excuses a span name
# kept out of docs/observability.md's table, and in a TEST file it marks
# a deliberately-bogus asserted name (fixture negatives) as not-a-typo.
# pilint v2 kinds: "blocking" now also vouches for a CALL SITE inside a
# lock region (the caller takes responsibility for the callee subtree,
# matching the runtime checker's any-frame suppression); "materialize"
# excuses an R8 forcing site, "probe" an R9 claim site, "stat" an R10
# unguarded stat site, "config" an R11 dataclass field kept off part of
# the config surface.
KNOWN_KINDS = ("swallow", "blocking", "counter", "mutation", "failpoint",
               "span", "materialize", "probe", "stat", "config")

_ANNOT_RE = re.compile(
    r"#\s*pilint:\s*allow-(?P<kind>[a-z][a-z-]*)\((?P<reason>[^)]*)\)"
)

MIN_REASON = 4


@dataclass
class Violation:
    path: str
    line: int
    rule: str  # "R1".."R5" or "A0" for annotation-grammar violations
    name: str  # short rule slug
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.name}: {self.message}"

    def sort_key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.rule)


@dataclass
class Annotation:
    line: int
    kind: str
    reason: str
    used: bool = False


@dataclass
class FileContext:
    """Everything a rule needs about one file, parsed once.

    v2 also hosts the shared walk caches: rules used to each re-walk the
    whole tree (7+ full walks per file); `nodes()` materializes one walk
    every rule iterates, `parents()` one child->parent map (guard-context
    checks), `graph()` one call graph (the interprocedural rules). The
    AST itself is parsed exactly once by the runner and shared here."""

    path: str  # repo-relative, forward slashes
    source: str
    tree: ast.AST
    annotations: List[Annotation] = field(default_factory=list)
    depth: int = 0  # interprocedural depth limit; 0 = runner default
    # line -> annotations covering that line (own line + line below)
    _by_line: Dict[int, List[Annotation]] = field(default_factory=dict)
    _nodes: Optional[List[ast.AST]] = field(default=None, repr=False)
    _parents: Optional[Dict[ast.AST, ast.AST]] = field(default=None, repr=False)
    _graph: object = field(default=None, repr=False)

    def __post_init__(self) -> None:
        for a in self.annotations:
            self._by_line.setdefault(a.line, []).append(a)
            self._by_line.setdefault(a.line + 1, []).append(a)

    def allowed(self, line: int, kind: str) -> bool:
        """True (and marks the annotation used) when `line` carries or sits
        directly under an `allow-<kind>` annotation."""
        for a in self._by_line.get(line, ()):
            if a.kind == kind:
                a.used = True
                return True
        return False

    def nodes(self) -> List[ast.AST]:
        """One full walk of the tree, materialized once and shared by
        every rule that previously re-walked it."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def parents(self) -> Dict[ast.AST, ast.AST]:
        """child -> parent map over the whole tree, built once."""
        if self._parents is None:
            self._parents = {
                child: node for node in self.nodes()
                for child in ast.iter_child_nodes(node)
            }
        return self._parents

    def graph(self):
        """The module call graph (tools/pilint/graph.py), built once and
        shared by the interprocedural rules (R3, R5, R8, R9)."""
        if self._graph is None:
            from .graph import ModuleGraph

            self._graph = ModuleGraph(self.tree)
        return self._graph

    def call_span_lines(self) -> Set[int]:
        """Every source line covered by some Call node — the runtime lock
        checker can only ever blame lines a call crosses, so an
        allow-blocking annotation covering none is provably rot."""
        lines: Set[int] = set()
        for node in self.nodes():
            if isinstance(node, ast.Call):
                end = getattr(node, "end_lineno", None) or node.lineno
                lines.update(range(node.lineno, end + 1))
        return lines


def _comment_lines(source: str) -> Optional[List[Tuple[int, str]]]:
    """(lineno, text) for every actual COMMENT token, so an annotation
    spelled inside a docstring or string literal (lockcheck.py documents
    the grammar in prose) is never parsed as a live annotation. None on
    tokenize failure — caller falls back to the raw-line scan."""
    try:
        out = [(tok.start[0], tok.string)
               for tok in tokenize.generate_tokens(io.StringIO(source).readline)
               if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None
    return out


def parse_annotations(path: str, source: str) -> Tuple[List[Annotation], List[Violation]]:
    """Extract annotations and grammar violations from comment tokens.

    Grammar violations (A0): unknown kind, missing/too-short reason. A
    malformed annotation is still RECORDED so the rule it meant to
    suppress stays suppressed — one finding per problem, not two."""
    annotations: List[Annotation] = []
    violations: List[Violation] = []
    lines = _comment_lines(source)
    if lines is None:
        lines = list(enumerate(source.splitlines(), start=1))
    for lineno, text in lines:
        for m in _ANNOT_RE.finditer(text):
            kind, reason = m.group("kind"), m.group("reason").strip()
            annotations.append(Annotation(line=lineno, kind=kind, reason=reason))
            if kind not in KNOWN_KINDS:
                violations.append(Violation(
                    path, lineno, "A0", "annotation-grammar",
                    f"unknown annotation kind 'allow-{kind}' "
                    f"(known: {', '.join('allow-' + k for k in KNOWN_KINDS)})",
                ))
            elif len(reason) < MIN_REASON:
                violations.append(Violation(
                    path, lineno, "A0", "annotation-grammar",
                    f"allow-{kind} needs a human-readable reason "
                    f"(got {reason!r})",
                ))
    return annotations, violations


def unused_annotation_violations(ctx: FileContext) -> List[Violation]:
    """Annotations that suppressed nothing are stale and must go — a rot
    check run AFTER all rules so `used` flags are final.

    `allow-blocking` keeps a NARROWED exemption: the runtime lock checker
    (pilosa_tpu/devtools/lockcheck.py) consumes the same grammar and
    honors the annotation on ANY frame of a blocking stack — so one that
    suppressed no static finding may still be load-bearing at runtime,
    but only if a call actually crosses a line it covers. A blocking
    annotation covering no call at all can never match a runtime frame
    either: that is rot from a refactor that moved the call, flag it."""
    out = []
    call_lines: Optional[Set[int]] = None
    for a in ctx.annotations:
        if a.kind not in KNOWN_KINDS or len(a.reason) < MIN_REASON or a.used:
            continue
        if a.kind == "blocking":
            if call_lines is None:
                call_lines = ctx.call_span_lines()
            if a.line in call_lines or a.line + 1 in call_lines:
                continue  # runtime-consumable: a call crosses its lines
            out.append(Violation(
                ctx.path, a.line, "A0", "annotation-grammar",
                "unused allow-blocking annotation (no call crosses this "
                "line or the line below, so neither the static pass nor "
                "the runtime lock checker can ever consume it) — delete it",
            ))
            continue
        out.append(Violation(
            ctx.path, a.line, "A0", "annotation-grammar",
            f"unused allow-{a.kind} annotation (nothing on this line "
            "or the line below triggers that rule) — delete it",
        ))
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last component of a Name/Attribute chain ('c' for a.b.c)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None
