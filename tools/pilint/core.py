"""Shared lint plumbing: violations, annotation grammar, file context.

The annotation grammar is deliberately rigid so it can be parsed with one
regex and audited by grep:

    # pilint: allow-<kind>(<reason>)

`kind` is one of the KNOWN_KINDS below and `reason` is mandatory prose
(>= 4 characters — "wip" does not explain anything to the next reader).
An annotation applies to the line it sits on and to the line directly
below it (so it can ride above a statement too long to share a line).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# One kind per rule that supports suppression. R2 (jax-free zones) has no
# escape hatch on purpose: a jax import in a config module is never
# acceptable — move the import into the function that needs it.
# "failpoint" is shared by both halves of R6: on a fire() site it excuses
# a name kept out of the docs table, and in a TEST file it marks a
# deliberately-bogus spec (registry/grammar tests) as not-a-typo.
# "span" mirrors it for R7: on a recording site it excuses a span name
# kept out of docs/observability.md's table, and in a TEST file it marks
# a deliberately-bogus asserted name (fixture negatives) as not-a-typo.
KNOWN_KINDS = ("swallow", "blocking", "counter", "mutation", "failpoint",
               "span")

_ANNOT_RE = re.compile(
    r"#\s*pilint:\s*allow-(?P<kind>[a-z][a-z-]*)\((?P<reason>[^)]*)\)"
)

MIN_REASON = 4


@dataclass
class Violation:
    path: str
    line: int
    rule: str  # "R1".."R5" or "A0" for annotation-grammar violations
    name: str  # short rule slug
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.name}: {self.message}"

    def sort_key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.rule)


@dataclass
class Annotation:
    line: int
    kind: str
    reason: str
    used: bool = False


@dataclass
class FileContext:
    """Everything a rule needs about one file, parsed once."""

    path: str  # repo-relative, forward slashes
    source: str
    tree: ast.AST
    annotations: List[Annotation] = field(default_factory=list)
    # line -> annotations covering that line (own line + line below)
    _by_line: Dict[int, List[Annotation]] = field(default_factory=dict)

    def __post_init__(self) -> None:
        for a in self.annotations:
            self._by_line.setdefault(a.line, []).append(a)
            self._by_line.setdefault(a.line + 1, []).append(a)

    def allowed(self, line: int, kind: str) -> bool:
        """True (and marks the annotation used) when `line` carries or sits
        directly under an `allow-<kind>` annotation."""
        for a in self._by_line.get(line, ()):
            if a.kind == kind:
                a.used = True
                return True
        return False


def parse_annotations(path: str, source: str) -> Tuple[List[Annotation], List[Violation]]:
    """Extract annotations and grammar violations from raw source.

    Grammar violations (A0): unknown kind, missing/too-short reason. A
    malformed annotation is still RECORDED so the rule it meant to
    suppress stays suppressed — one finding per problem, not two."""
    annotations: List[Annotation] = []
    violations: List[Violation] = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        for m in _ANNOT_RE.finditer(text):
            kind, reason = m.group("kind"), m.group("reason").strip()
            annotations.append(Annotation(line=lineno, kind=kind, reason=reason))
            if kind not in KNOWN_KINDS:
                violations.append(Violation(
                    path, lineno, "A0", "annotation-grammar",
                    f"unknown annotation kind 'allow-{kind}' "
                    f"(known: {', '.join('allow-' + k for k in KNOWN_KINDS)})",
                ))
            elif len(reason) < MIN_REASON:
                violations.append(Violation(
                    path, lineno, "A0", "annotation-grammar",
                    f"allow-{kind} needs a human-readable reason "
                    f"(got {reason!r})",
                ))
    return annotations, violations


def unused_annotation_violations(ctx: FileContext) -> List[Violation]:
    """Annotations that suppressed nothing are stale and must go — a rot
    check run AFTER all rules so `used` flags are final.

    `allow-blocking` is exempt: the runtime lock checker
    (pilosa_tpu/devtools/lockcheck.py) consumes the same grammar for
    calls that only BECOME lock-held dynamically (an fsync inside a
    helper its caller locks around), which this static pass can't see."""
    out = []
    for a in ctx.annotations:
        if a.kind == "blocking":
            continue
        if a.kind in KNOWN_KINDS and len(a.reason) >= MIN_REASON and not a.used:
            out.append(Violation(
                ctx.path, a.line, "A0", "annotation-grammar",
                f"unused allow-{a.kind} annotation (nothing on this line "
                "or the line below triggers that rule) — delete it",
            ))
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last component of a Name/Attribute chain ('c' for a.b.c)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None
