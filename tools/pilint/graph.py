"""Module-level call graph + interprocedural helpers (pilint v2).

PR 8's rules were lexical and per-file: anything one call deep was
invisible, and PRs 9/12 each paid multiple review rounds for the same
bug classes hiding in helpers (an fsync inside a method its caller locks
around, a device result materialized in a callee of the guard). This
module is the shared foundation the interprocedural rules (R3, R5, R8,
R9) stand on:

- ``ModuleGraph``: every function/method/nested-def in one module as a
  node, with call edges resolved conservatively — ``self.m()`` /
  ``cls.m()`` to the enclosing class's method, bare names to a nested
  def of an enclosing function or a module-level function. Unresolvable
  calls (other objects, imports) are simply absent: the analysis is
  may-analysis over what it can see, never a guess.
- may-hold-lock propagation: starting from ``with <lock>:`` bodies,
  walk resolved edges up to a config-bounded depth so a helper that
  blocks under its caller's lock is attributed with the full call chain.

The depth limit (``DEFAULT_DEPTH``, CLI ``--depth``) bounds every walk;
cycles terminate via a best-depth visited map regardless.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import terminal_name

# One knob for every interprocedural walk (R3 lock-flow, R5 bump reach,
# R8 guard domination). Four call edges covers the deepest real chain in
# the tree with one level of headroom; a helper nest deeper than that is
# its own code smell.
DEFAULT_DEPTH = 4

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_BODY = _FUNC_NODES + (ast.Lambda,)


@dataclass
class CallSite:
    """One call inside a function's own body (nested defs excluded —
    their bodies run when *they* are called, which is its own edge)."""

    node: ast.Call
    lineno: int
    callee: Optional[str]  # resolved qualname, or None (out of scope)


@dataclass
class FuncNode:
    qualname: str  # "Cls.meth", "func", "Cls.meth.<nested>"
    name: str
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    cls: Optional[str]  # enclosing class name, if a method
    parent: Optional[str]  # enclosing function qualname, if nested
    calls: List[CallSite] = field(default_factory=list)
    nested: Dict[str, str] = field(default_factory=dict)  # name -> qualname


def own_body_walk(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Yield every node of a function's OWN body: nested function and
    lambda bodies are pruned (they execute at their call sites, not
    here — the graph models that with explicit edges)."""
    todo = list(ast.iter_child_nodes(fn_node))
    while todo:
        node = todo.pop()
        if isinstance(node, _SKIP_BODY):
            continue
        yield node
        todo.extend(ast.iter_child_nodes(node))


class ModuleGraph:
    """Call graph over one module's AST. Built once per FileContext and
    shared by every rule that needs callee reach."""

    def __init__(self, tree: ast.AST):
        self.functions: Dict[str, FuncNode] = {}
        self.methods_of: Dict[str, Dict[str, str]] = {}  # cls -> name -> qual
        self.module_funcs: Dict[str, str] = {}  # name -> qualname
        self._collect(tree.body, cls=None, parent=None)
        for fn in self.functions.values():
            self._resolve_calls(fn)

    # ------------------------------------------------------------ building

    def _collect(self, body, cls: Optional[str], parent: Optional[str]) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                # Class bodies reset the function-nesting context: a
                # method of a nested class is that class's method.
                self._collect(node.body, cls=node.name, parent=None)
            elif isinstance(node, _FUNC_NODES):
                qual = (f"{parent}.{node.name}" if parent
                        else f"{cls}.{node.name}" if cls else node.name)
                fn = FuncNode(qualname=qual, name=node.name, node=node,
                              cls=cls, parent=parent)
                self.functions[qual] = fn
                if parent is None and cls is not None:
                    self.methods_of.setdefault(cls, {})[node.name] = qual
                elif parent is None and cls is None:
                    self.module_funcs[node.name] = qual
                else:
                    p = self.functions.get(parent)
                    if p is not None:
                        p.nested[node.name] = qual
                self._collect(node.body, cls=cls, parent=qual)
            else:
                # Compound statements can hide defs at ANY nesting depth
                # (an except-handler's fallback def, an if inside a try);
                # descend to each def/class boundary and recurse — the
                # import-fallback idiom is exactly where blocking host
                # helpers live.
                todo = list(ast.iter_child_nodes(node))
                while todo:
                    sub = todo.pop()
                    if isinstance(sub, (ast.ClassDef,) + _FUNC_NODES):
                        self._collect([sub], cls=cls, parent=parent)
                        continue
                    if isinstance(sub, ast.Lambda):
                        continue
                    todo.extend(ast.iter_child_nodes(sub))

    def _resolve_calls(self, fn: FuncNode) -> None:
        for node in own_body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            fn.calls.append(CallSite(
                node=node, lineno=node.lineno,
                callee=self.resolve(fn, node)))

    def resolve(self, fn: FuncNode, call: ast.Call) -> Optional[str]:
        """Conservative callee resolution inside `fn`; None when the
        target is outside this module's static view."""
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id in ("self", "cls") and fn.cls is not None:
                return self.methods_of.get(fn.cls, {}).get(f.attr)
            return None
        if isinstance(f, ast.Name):
            # Innermost-first lexical scope: nested defs of the enclosing
            # function chain, then module-level functions.
            cur: Optional[FuncNode] = fn
            while cur is not None:
                if f.id in cur.nested:
                    return cur.nested[f.id]
                cur = self.functions.get(cur.parent) if cur.parent else None
            return self.module_funcs.get(f.id)
        return None

    # ----------------------------------------------------------- lock flow

    def lock_regions(self, is_lock) -> List[Tuple[FuncNode, ast.With, str]]:
        """Every `with <lock>:` statement, paired with the function whose
        body it sits in (resolution context for calls inside it)."""
        out: List[Tuple[FuncNode, ast.With, str]] = []
        for fn in self.functions.values():
            for node in own_body_walk(fn.node):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if is_lock(item.context_expr):
                            name = terminal_name(item.context_expr) or "<lock>"
                            out.append((fn, node, name))
                            break
        return out

    def reach(self, seeds: List[Tuple[str, int, str]], depth_limit: int,
              follow_edge=None):
        """May-reach walk: from `seeds` [(qualname, seed_lineno, label)],
        yield (FuncNode, depth, chain) for every function reachable
        within `depth_limit` call edges. `chain` is the human-readable
        call path ("with self._mu (line 10) -> self._flush (line 12)").
        `follow_edge(call_site) -> bool` can veto an edge (annotation
        vouching). Cycles terminate via a best-depth visited map."""
        best: Dict[str, int] = {}
        todo = [(qual, 1, label) for qual, _line, label in seeds]
        while todo:
            qual, depth, chain = todo.pop(0)
            if depth > depth_limit:
                continue
            fn = self.functions.get(qual)
            if fn is None or best.get(qual, depth_limit + 1) <= depth:
                continue
            best[qual] = depth
            yield fn, depth, chain
            for site in fn.calls:
                if site.callee is None:
                    continue
                if follow_edge is not None and not follow_edge(site):
                    continue
                todo.append((
                    site.callee, depth + 1,
                    f"{chain} -> {_callee_label(site)} (line {site.lineno})"))

    # ------------------------------------------------------------- queries

    def class_methods(self, cls: str) -> Dict[str, str]:
        return self.methods_of.get(cls, {})


def _callee_label(site: CallSite) -> str:
    f = site.node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f"{f.value.id}.{f.attr}"
    return terminal_name(f) or "<call>"
