"""pilint: project-specific invariant lint for pilosa-tpu.

Seven PRs of review notes distilled into machine-checkable rules
(docs/static-analysis.md has the full contract):

  R1 swallowed-exceptions   broad `except Exception` handlers must log,
                            count, capture, or re-raise; broad guards
                            around imports must catch ImportError.
  R2 jax-free-zones         config-surface modules stay importable
                            without jax (no module-level jax imports).
  R3 blocking-under-lock    no deny-listed blocking call (sleep, fsync,
                            socket/HTTP send, device_put, engine gather)
                            lexically inside a `with <lock>:` block.
  R4 counter-hygiene        every literal-keyed counter increment is
                            reachable from /debug/vars (a wholesale
                            `snapshot()` export or an explicit literal in
                            handler.py/diagnostics.py).
  R5 mutation-epoch-audit   core/ methods that mutate bitmap storage
                            must reach a generation/epoch bump through
                            the same-class call graph.

Escape hatch: `# pilint: allow-<rule>(<reason>)` on the flagged line or
the line above, with a mandatory human-readable reason. Unknown kinds,
empty reasons, and annotations that suppress nothing are themselves
violations, so the allow-list cannot rot silently.

Run: `python -m tools.pilint pilosa_tpu/` (exit 1 on violations).
Stdlib `ast` only — no third-party dependencies.
"""

from .core import Violation, Annotation, parse_annotations
from .runner import lint_paths, lint_file, format_report

__all__ = [
    "Violation",
    "Annotation",
    "parse_annotations",
    "lint_paths",
    "lint_file",
    "format_report",
]
