"""pilint: project-specific invariant lint for pilosa-tpu.

Review notes from a dozen PRs distilled into machine-checkable rules
(docs/static-analysis.md has the full contract). v2 is interprocedural:
a per-module call graph (tools/pilint/graph.py) with a config-bounded
depth limit backs R3/R5/R8/R9, so a bug one call deep is no longer
invisible.

  R1  swallowed-exceptions   broad `except Exception` handlers must log,
                             count, capture, or re-raise; broad guards
                             around imports must catch ImportError.
  R2  jax-free-zones         config-surface modules stay importable
                             without jax (no module-level jax imports).
  R3  blocking-under-lock    no deny-listed blocking call (sleep, fsync,
                             socket/HTTP send, device_put, engine gather)
                             inside a `with <lock>:` block — directly OR
                             through resolved callees (lock-flow).
  R4  counter-hygiene        every literal-keyed counter increment is
                             reachable from /debug/vars (a wholesale
                             `snapshot()` export or an explicit literal in
                             handler.py/diagnostics.py).
  R5  mutation-epoch-audit   core/ methods that mutate bitmap storage
                             must reach a generation/epoch bump through
                             the same-class call graph.
  R6  failpoint-hygiene      fire sites documented; test activation
                             specs name real fire sites.
  R7  span-hygiene           recorder span names documented; trace
                             assertions name real recording sites.
  R8  guarded-materialization device results force to host inside the
                             _device_call/ladder guard (engine/collective).
  R9  probe-claim-hygiene    multi-breaker probe claims are dominated by
                             a side-effect-free due check (health modules).
  R10 none-guarded-stats     stat sites survive stats-less holders
                             (route through _count_stat-style guards).
  R11 config-surface         every section *Config field reaches TOML
                             parse + dump, env, CLI flag, and its doc.

Escape hatch: `# pilint: allow-<kind>(<reason>)` on the flagged line or
the line above, with a mandatory human-readable reason. Unknown kinds,
empty reasons, and annotations that suppress nothing are themselves
violations, so the allow-list cannot rot silently.

Run: `python -m tools.pilint pilosa_tpu/` (exit 1 on violations);
`--changed [REF]` for the incremental mode, `--depth N` for the
interprocedural limit. Stdlib `ast` only — no third-party dependencies.
"""

from .core import Violation, Annotation, parse_annotations
from .runner import lint_paths, lint_file, format_report

__all__ = [
    "Violation",
    "Annotation",
    "parse_annotations",
    "lint_paths",
    "lint_file",
    "format_report",
]
