"""CLI: `python -m tools.pilint pilosa_tpu/ [more paths] [--rule R1,R3]`.

Exit status: 0 clean, 1 violations, 2 usage error. Run from the repo
root (or pass --root) so zone/wiring paths resolve.
"""

from __future__ import annotations

import argparse
import sys

from .runner import format_report, lint_paths
from .rules import ALL_RULES


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.pilint",
        description="pilosa-tpu invariant lint (see docs/static-analysis.md)",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories to lint (default: pilosa_tpu/)")
    parser.add_argument("--rule", help="comma-separated subset, e.g. R1,R3 "
                        "(disables the unused-annotation check)")
    parser.add_argument("--root", default=None,
                        help="repo root for relative-path rules (default: cwd)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id, fn in ALL_RULES:
            print(f"{rule_id}  {fn.__name__.removeprefix('rule_')}")
        return 0

    paths = args.paths or ["pilosa_tpu"]
    rules = None
    if args.rule:
        rules = [r.strip().upper() for r in args.rule.split(",") if r.strip()]
        known = {rid for rid, _ in ALL_RULES}
        bad = [r for r in rules if r not in known]
        if bad:
            print(f"unknown rule(s): {', '.join(bad)}", file=sys.stderr)
            return 2

    violations = lint_paths(paths, repo_root=args.root, rules=rules)
    print(format_report(violations))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
