"""CLI: `python -m tools.pilint pilosa_tpu/ [more paths] [--rule R1,R3]`.

Exit status: 0 clean, 1 violations, 2 usage error. Run from the repo
root (or pass --root) so zone/wiring paths resolve. `--changed [REF]`
lints only files changed relative to REF (default HEAD) plus untracked
files — the pre-commit-cheap incremental mode; cross-file corpora (R6,
R7, R11) are still gathered from the full tree.
"""

from __future__ import annotations

import argparse
import os
import sys

from .graph import DEFAULT_DEPTH
from .runner import changed_files, format_report, lint_paths
from .rules import ALL_RULES


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.pilint",
        description="pilosa-tpu invariant lint (see docs/static-analysis.md)",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories to lint (default: pilosa_tpu/)")
    parser.add_argument("--rule", help="comma-separated subset, e.g. R1,R3 "
                        "(disables the unused-annotation check)")
    parser.add_argument("--root", default=None,
                        help="repo root for relative-path rules (default: cwd)")
    parser.add_argument("--depth", type=int, default=DEFAULT_DEPTH,
                        help="interprocedural call-depth limit for the "
                        f"dataflow rules (default: {DEFAULT_DEPTH})")
    parser.add_argument("--changed", nargs="?", const="HEAD", default=None,
                        metavar="REF",
                        help="lint only files in `git diff --name-only REF` "
                        "(default REF: HEAD) plus untracked .py files")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id, fn in ALL_RULES:
            print(f"{rule_id}  {fn.__name__.removeprefix('rule_')}")
        return 0

    if args.depth < 1:
        print("--depth must be >= 1", file=sys.stderr)
        return 2

    rules = None
    if args.rule:
        rules = [r.strip().upper() for r in args.rule.split(",") if r.strip()]
        known = {rid for rid, _ in ALL_RULES}
        bad = [r for r in rules if r not in known]
        if bad:
            print(f"unknown rule(s): {', '.join(bad)}", file=sys.stderr)
            return 2

    root = args.root or os.getcwd()
    if args.changed is not None:
        if args.paths:
            print("--changed and explicit paths are mutually exclusive",
                  file=sys.stderr)
            return 2
        try:
            paths = changed_files(args.changed, root)
        except RuntimeError as e:
            print(str(e), file=sys.stderr)
            return 2
        if not paths:
            print("pilint: 0 violations (no changed .py files)")
            return 0
    else:
        paths = args.paths or ["pilosa_tpu"]

    violations = lint_paths(paths, repo_root=args.root, rules=rules,
                            depth=args.depth)
    print(format_report(violations))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
