"""The pilint rules (R1-R11). Each rule is a function(ctx, env) -> [Violation].

`env` is a RepoEnv carrying the cross-file facts some rules need (R4's
/debug/vars wiring corpus, R6/R7's docs+site corpora, R11's config
surface). Rules are pure AST walks over shared caches — no imports of
the linted code, so a file with a missing optional dependency still
lints; the interprocedural rules (R3, R5, R8, R9) additionally share
the per-module call graph from tools/pilint/graph.py.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import (FileContext, Violation, dotted_name, parse_annotations,
                   terminal_name)
from .graph import DEFAULT_DEPTH, ModuleGraph, own_body_walk

# --------------------------------------------------------------------------
# cross-file environment


@dataclass
class RepoEnv:
    """Facts gathered once per run, consumed by individual rules.

    wired_literals: every string literal in the /debug/vars wiring files
        (server/handler.py, diagnostics.py) — a counter key appearing
        there is observable by an operator.
    stats_wholesale: True when handler.py dumps `stats.snapshot()`
        wholesale into /debug/vars, which makes every `stats.count(name)`
        counter observable without listing its name.
    failpoint_doc_names: failpoint names listed in docs/durability.md's
        reference table (R6: every fire() site must appear there).
    failpoint_docs_loaded: True when the docs file was actually read —
        R6's fire-site half no-ops otherwise, so fixture runs that lint
        a lone snippet without wiring the docs don't false-positive.
    failpoint_fire_sites: every name passed to failpoints.fire() across
        pilosa_tpu/ (R6: a test activation spec must name one of these —
        a typo'd spec silently turns a fault test into a no-op).
    failpoint_spec_sites: (path, line, name) of every failpoint name a
        test activates/configures, with allow-failpoint-annotated lines
        already filtered out.
    span_doc_names: span names listed in docs/observability.md's span
        reference table (R7: every recorder span name must appear there).
    span_docs_loaded: True when that doc was actually read — R7's
        recording-site half no-ops otherwise (fixture runs).
    span_record_sites: every constant span name passed to a recorder call
        across pilosa_tpu/ (R7: a name a test asserts on must name one of
        these — a typo'd assertion tests a span that never records).
    span_assert_sites: (path, line, name) of every span name a test
        asserts on (assert_span/find_span helper calls), allow-span-
        annotated lines already filtered out.
    """

    wired_literals: Set[str] = field(default_factory=set)
    stats_wholesale: bool = False
    failpoint_doc_names: Set[str] = field(default_factory=set)
    failpoint_docs_loaded: bool = False
    failpoint_fire_sites: Set[str] = field(default_factory=set)
    failpoint_spec_sites: List = field(default_factory=list)
    span_doc_names: Set[str] = field(default_factory=set)
    span_docs_loaded: bool = False
    span_record_sites: Set[str] = field(default_factory=set)
    span_assert_sites: List = field(default_factory=list)
    # R11 (config-surface completeness): every string constant in
    # config.py (TOML keys, env spellings, flag-mapping keys, to_toml
    # dump lines — f-string constant parts included) and cli.py (flag
    # spellings), plus the text of each section's reference doc. The
    # rule no-ops until config_surface_loaded so fixture runs that lint
    # a lone dataclass snippet without the corpus never false-positive.
    config_surface_loaded: bool = False
    config_constants: Set[str] = field(default_factory=set)
    cli_constants: Set[str] = field(default_factory=set)
    config_docs: Dict[str, str] = field(default_factory=dict)
    # Per-SECTION scoping for the parse/dump halves: a TOML key shared
    # by two sections (`delta-max-fraction` in [engine] and
    # [collective], `key` in [gossip] and [tls]) must not let one
    # section's spelling mask the other's drift. config_set_attrs holds
    # every dotted attribute-store chain in config.py (the _apply_dict
    # parse surface, `self.engine.plan_cache = ...`); config_dump_rows
    # maps a to_toml section header to the row constants inside it.
    config_set_attrs: Set[str] = field(default_factory=set)
    config_dump_rows: Dict[str, Set[str]] = field(default_factory=dict)


WIRING_FILES = ("pilosa_tpu/server/handler.py", "pilosa_tpu/diagnostics.py")
# R6's reference table lives in the durability doc (the failpoint section).
FAILPOINT_DOC = "docs/durability.md"
# R7's reference table lives in the observability doc (the span section).
SPAN_DOC = "docs/observability.md"


def build_env(sources: Dict[str, str]) -> RepoEnv:
    env = RepoEnv()
    for rel in WIRING_FILES:
        src = sources.get(rel)
        if src is None:
            continue
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                env.wired_literals.add(node.value)
            if (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "snapshot"
                    and isinstance(node.func, ast.Attribute)
                    and terminal_name(node.func.value) == "stats"):
                env.stats_wholesale = True
    return env


# --------------------------------------------------------------------------
# R1: no swallowed exceptions


_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}
_BROAD = {"Exception", "BaseException"}


def _is_broad(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:  # bare except:
        return True
    if isinstance(t, ast.Tuple):
        return any(terminal_name(e) in _BROAD for e in t.elts)
    return terminal_name(t) in _BROAD


def _body_handles(h: ast.ExceptHandler) -> bool:
    """True when the handler body re-raises, logs, counts, or captures the
    exception for later use — i.e. the failure leaves a trace."""
    exc_name = h.name
    for node in ast.walk(ast.Module(body=list(h.body), type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute):
                base = terminal_name(fn.value) or ""
                # self.logger.error(...), logging.warning(...), log.info(...)
                if fn.attr in _LOG_METHODS and "log" in base.lower():
                    return True
                # stats.count("X", n) / self._stats.add_pending(...)
                if fn.attr == "count":
                    return True
        # counters["x"] += 1 / self.quarantined_reads += 1
        if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
            tgt = node.target
            if isinstance(tgt, ast.Subscript) or isinstance(tgt, ast.Attribute):
                return True
        # `except ... as e` whose body USES e (stores it, appends it,
        # formats it into a result): the error is captured, not dropped.
        if (exc_name and isinstance(node, ast.Name)
                and node.id == exc_name and isinstance(node.ctx, ast.Load)):
            return True
    return False


def _try_body_imports(handler: ast.ExceptHandler, tree: ast.AST) -> bool:
    """True when `handler` belongs to a Try whose body is import work."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Try) and handler in node.handlers:
            return any(isinstance(s, (ast.Import, ast.ImportFrom))
                       for s in node.body)
    return False


def rule_swallow(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    out: List[Violation] = []
    for node in ctx.nodes():
        if not isinstance(node, ast.ExceptHandler) or not _is_broad(node):
            continue
        if _try_body_imports(node, ctx.tree):
            # No annotation escape: a broad guard around an import hides
            # typos inside the guarded module forever. Catch ImportError.
            out.append(Violation(
                ctx.path, node.lineno, "R1", "swallowed-exceptions",
                "broad except around an import guard — catch ImportError "
                "(a typo inside the imported module currently vanishes)",
            ))
            continue
        if _body_handles(node):
            continue
        if ctx.allowed(node.lineno, "swallow"):
            continue
        out.append(Violation(
            ctx.path, node.lineno, "R1", "swallowed-exceptions",
            "broad except swallows the error: log it, count it into "
            "/debug/vars, re-raise, narrow the type, or annotate "
            "`# pilint: allow-swallow(reason)`",
        ))
    return out


# --------------------------------------------------------------------------
# R2: jax-free zones


# Modules the configuration surface imports at CLI startup; they must
# stay importable on a box with no jax (docs/static-analysis.md).
JAX_FREE_ZONES = (
    "pilosa_tpu/config.py",
    "pilosa_tpu/ingest.py",
    "pilosa_tpu/tier/__init__.py",
    "pilosa_tpu/parallel/__init__.py",
    "pilosa_tpu/sched/",
    "pilosa_tpu/obs/",
    "pilosa_tpu/plan/",
    "pilosa_tpu/cdc/",
    "pilosa_tpu/geo/",
    "pilosa_tpu/server/mux.py",
)


def _in_zone(path: str) -> bool:
    return any(path == z or (z.endswith("/") and path.startswith(z))
               for z in JAX_FREE_ZONES)


def rule_jax_free(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    if not _in_zone(ctx.path):
        return []
    out: List[Violation] = []

    def check(body, toplevel: bool) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # deferred to call time: allowed
            if isinstance(node, ast.If):
                test = node.test
                if terminal_name(test) == "TYPE_CHECKING":
                    # The if-body is typing-only and never executes, but an
                    # `else:` branch DOES run at import time — keep checking it.
                    check(node.orelse, toplevel)
                    continue
                check(node.body, toplevel)
                check(node.orelse, toplevel)
                continue
            if isinstance(node, (ast.Try, ast.With, ast.AsyncWith,
                                 ast.ClassDef, ast.For, ast.AsyncFor,
                                 ast.While)):
                # Every statement list of a compound statement executes at
                # import time (only def bodies defer): try/else/finally,
                # loop bodies and their else clauses included.
                check(node.body, toplevel)
                if isinstance(node, ast.Try):
                    for h in node.handlers:
                        check(h.body, toplevel)
                    check(node.orelse, toplevel)
                    check(node.finalbody, toplevel)
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    check(node.orelse, toplevel)
                continue
            names = []
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                names = [node.module]
            for n in names:
                if n == "jax" or n.startswith("jax."):
                    out.append(Violation(
                        ctx.path, node.lineno, "R2", "jax-free-zones",
                        f"module-level `import {n}` in a jax-free zone — "
                        "move it inside the function that needs it",
                    ))

    check(ctx.tree.body, True)
    return out


# --------------------------------------------------------------------------
# R3: no blocking calls under a lock


_LOCK_NAME_RE = re.compile(
    r"(?:^|_)(lock|rlock|mu|mutex|cv|cond)\d*$", re.IGNORECASE
)

# Deny-listed *direct* calls inside a `with <lock>:` block. This is a
# lexical check — calls that block transitively are the runtime lock
# checker's job (pilosa_tpu/devtools/lockcheck.py). Each entry is either
# a full dotted name or ('*', terminal_attr).
_DENY_DOTTED = {
    "time.sleep", "_time.sleep",
    "os.fsync", "os.fdatasync", "os.replace", "os.rename",
    "shutil.move", "shutil.copyfile",
    "jax.device_put",
    "socket.create_connection",
    "urllib.request.urlopen",
}
_DENY_TERMINAL = {
    # socket / HTTP client sends
    "urlopen", "getresponse", "sendall", "create_connection",
    "send_message",
    # device transfers + engine gathers (serialize off-lock: PR 5/7 rules)
    "device_put", "block_until_ready", "_gather_leaf",
    "_stacked_leaf_tensor",
    # durability syscalls regardless of the module alias
    "fsync", "fdatasync",
}


def _is_lock_name(expr: ast.AST) -> bool:
    name = terminal_name(expr)
    return bool(name and _LOCK_NAME_RE.search(name))


def _deny_match(call: ast.Call) -> Optional[str]:
    dn = dotted_name(call.func)
    if dn in _DENY_DOTTED:
        return dn
    term = terminal_name(call.func)
    if term in _DENY_TERMINAL:
        return dn or term
    return None


def _region_calls(stmts) -> List[ast.Call]:
    """Every call lexically inside a held-lock region, pruning nested
    function/lambda bodies (they run later, lock not necessarily held)."""
    out: List[ast.Call] = []
    todo = list(stmts)
    while todo:
        node = todo.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        todo.extend(ast.iter_child_nodes(node))
    return out


def rule_blocking_under_lock(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R3, interprocedural since pilint v2: the lexical half flags
    deny-listed calls directly inside a `with <lock>:` block; the
    dataflow half propagates the may-hold-lock fact through resolved
    same-class / module-function call edges (depth-bounded), so a helper
    that fsyncs or sleeps under its CALLER's lock is caught with the
    full chain — the PR 8/9 review-round class the per-file rule missed.
    An `allow-blocking` annotation on a call site inside the region
    vouches for the whole callee subtree, mirroring the runtime
    checker's any-frame suppression."""
    out: List[Violation] = []
    reported: Set[int] = set()
    graph = ctx.graph()
    depth_limit = ctx.depth or DEFAULT_DEPTH

    def flag(call: ast.Call, hit: str, how: str) -> None:
        if call.lineno in reported:
            return
        if ctx.allowed(call.lineno, "blocking"):
            return
        reported.add(call.lineno)
        out.append(Violation(
            ctx.path, call.lineno, "R3", "blocking-under-lock",
            f"blocking call `{hit}` {how} — serialize off-lock "
            "(docs/durability.md, docs/tiered-storage.md) or annotate "
            "`# pilint: allow-blocking(reason)`",
        ))

    seeds: List[Tuple[str, int, str]] = []
    seen_regions: Set[int] = set()
    for fn, with_node, lock_name in graph.lock_regions(_is_lock_name):
        if id(with_node) in seen_regions:
            continue
        seen_regions.add(id(with_node))
        region = f"`with {lock_name}:` (line {with_node.lineno})"
        for call in _region_calls(with_node.body):
            hit = _deny_match(call)
            if hit:
                flag(call, hit, "inside a `with <lock>:` block")
            callee = graph.resolve(fn, call)
            if callee is not None and not ctx.allowed(call.lineno, "blocking"):
                label = dotted_name(call.func) or terminal_name(call.func)
                seeds.append((callee, call.lineno,
                              f"{region} -> {label} (line {call.lineno})"))
    # Module-level / class-body lock regions (outside any function) get
    # the direct lexical scan AND seed the walk for bare-name calls to
    # module functions — the graph's lock_regions only walks function
    # bodies, and a `with _boot_lock: _warm()` helper must not hide.
    for node in ctx.nodes():
        if (isinstance(node, (ast.With, ast.AsyncWith))
                and id(node) not in seen_regions
                and any(_is_lock_name(i.context_expr) for i in node.items)):
            lock_name = next(
                (terminal_name(i.context_expr) for i in node.items
                 if _is_lock_name(i.context_expr)), "<lock>")
            region = f"`with {lock_name}:` (line {node.lineno})"
            for call in _region_calls(node.body):
                hit = _deny_match(call)
                if hit:
                    flag(call, hit, "inside a `with <lock>:` block")
                if (isinstance(call.func, ast.Name)
                        and call.func.id in graph.module_funcs
                        and not ctx.allowed(call.lineno, "blocking")):
                    seeds.append((graph.module_funcs[call.func.id],
                                  call.lineno,
                                  f"{region} -> {call.func.id} "
                                  f"(line {call.lineno})"))

    def follow(site) -> bool:
        # The caller can vouch for a callee subtree with an annotation
        # on the call-site line (the runtime checker honors any frame).
        return not ctx.allowed(site.lineno, "blocking")

    for fnode, _depth, chain in graph.reach(seeds, depth_limit, follow):
        for node in own_body_walk(fnode.node):
            if isinstance(node, ast.Call):
                hit = _deny_match(node)
                if hit:
                    flag(node, hit,
                         f"reached while a lock is held: {chain} -> "
                         f"`{fnode.name}` blocks at line {node.lineno}")
    return out


# --------------------------------------------------------------------------
# R4: counter hygiene


def _is_self_counters(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "counters"
            and terminal_name(node.value) == "self")


def _class_has_wholesale_snapshot(cls: ast.ClassDef) -> bool:
    # A snapshot() only counts as wholesale when it exports the WHOLE
    # counter dict — `dict(self.counters)`, `self.counters.copy()`,
    # `{**self.counters, ...}`, or `return self.counters` — not merely any
    # mention of self.counters. A partial export (`self.counters['hits']`)
    # must NOT grant the whole class R4 immunity.
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "snapshot":
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "dict"
                        and any(_is_self_counters(a) for a in sub.args)):
                    return True
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "copy"
                        and _is_self_counters(sub.func.value)):
                    return True
                if isinstance(sub, ast.Dict) and any(
                        k is None and _is_self_counters(v)
                        for k, v in zip(sub.keys, sub.values)):
                    return True
                if isinstance(sub, ast.Return) and _is_self_counters(sub.value):
                    return True
    return False


def rule_counter_hygiene(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    if not ctx.path.startswith("pilosa_tpu/"):
        return []
    out: List[Violation] = []

    def scan(body, wholesale: bool) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                scan(node.body, _class_has_wholesale_snapshot(node))
                continue
            # BFS that PRUNES nested ClassDefs (classes inside functions):
            # each is re-dispatched through scan() so its increments are
            # judged against its OWN snapshot(), not the enclosing class's.
            todo = [node]
            while todo:
                sub = todo.pop(0)
                if isinstance(sub, ast.ClassDef):
                    scan(sub.body, _class_has_wholesale_snapshot(sub))
                    continue
                todo.extend(ast.iter_child_nodes(sub))
                # counters["key"] += n
                if (isinstance(sub, ast.AugAssign)
                        and isinstance(sub.target, ast.Subscript)
                        and terminal_name(sub.target.value) == "counters"):
                    sl = sub.target.slice
                    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                        key = sl.value
                        if (not wholesale
                                and key not in env.wired_literals
                                and not ctx.allowed(sub.lineno, "counter")):
                            out.append(Violation(
                                ctx.path, sub.lineno, "R4", "counter-hygiene",
                                f"counter {key!r} is incremented but not "
                                "reachable from /debug/vars: export it via a "
                                "wholesale snapshot() or wire the literal in "
                                "handler.py/diagnostics.py",
                            ))
                # stats.count("Name", n)
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "count" and sub.args):
                    a0 = sub.args[0]
                    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                        name = a0.value
                        if (not env.stats_wholesale
                                and name not in env.wired_literals
                                and not ctx.allowed(sub.lineno, "counter")):
                            out.append(Violation(
                                ctx.path, sub.lineno, "R4", "counter-hygiene",
                                f"stats counter {name!r} is not surfaced: "
                                "/debug/vars no longer dumps stats.snapshot() "
                                "wholesale and the name appears nowhere in "
                                "the wiring files",
                            ))

    scan(ctx.tree.body, False)
    return out


# --------------------------------------------------------------------------
# R6: failpoint hygiene


# Must track pilosa_tpu/failpoints.py's _SPEC_RE action set: a string is
# only treated as an activation spec when its right-hand side parses as a
# real action, so ordinary "key=value" literals never false-positive.
_FP_NAME = r"[a-z][a-z0-9_.-]*"
_FP_SPEC_PART_RE = re.compile(
    rf"^(?P<name>{_FP_NAME})(?:@[^=;\s]+)?="
    r"(?:\d+\*)?(?:error|crash|drop|oom|latency|flaky)(?:\([^)]*\))?$"
)
_FP_NAME_RE = re.compile(rf"^{_FP_NAME}(?:@.+)?$")


def parse_failpoint_docs(text: str) -> Set[str]:
    """Failpoint names from the reference table in docs/durability.md:
    table rows (lines starting with `|`) inside the `## Failpoints`
    section whose first cell is a backticked name."""
    names: Set[str] = set()
    in_section = False
    for line in text.splitlines():
        if line.startswith("## "):
            in_section = "failpoint" in line.lower()
            continue
        if in_section:
            m = re.match(rf"\|\s*`({_FP_NAME})`", line)
            if m:
                names.add(m.group(1))
    return names


def collect_fire_names(tree: ast.AST) -> Set[str]:
    """Every string literal passed as the first arg of a fire() call."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) == "fire" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.add(node.args[0].value)
    return out


def collect_spec_sites(path: str, source: str) -> List:
    """(path, line, base-name) for every failpoint a test activates:
    string literals that parse as `name[@target]=action` specs (activate()
    / PILOSA_TPU_FAILPOINTS values) plus plain-string first args of
    configure(). Lines carrying `# pilint: allow-failpoint(reason)` are
    excluded — registry/grammar tests use deliberately-bogus names."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    annotations, _ = parse_annotations(path, source)
    ctx = FileContext(path=path, source=source, tree=tree,
                      annotations=annotations)
    out: List = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for part in node.value.split(";"):
                m = _FP_SPEC_PART_RE.match(part.strip())
                if m and not ctx.allowed(node.lineno, "failpoint"):
                    out.append((path, node.lineno, m.group("name")))
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) == "configure" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            name = node.args[0].value
            if (_FP_NAME_RE.match(name)
                    and not ctx.allowed(node.lineno, "failpoint")):
                out.append((path, node.lineno, name.split("@")[0]))
    return out


def rule_failpoint_hygiene(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R6a: every fire("<name>") site in pilosa_tpu/ must appear in the
    docs/durability.md reference table — the table is how tests and
    operators discover injection points, and an undocumented point is
    one nobody will ever activate."""
    if not ctx.path.startswith("pilosa_tpu/") or not env.failpoint_docs_loaded:
        return []
    out: List[Violation] = []
    for node in ctx.nodes():
        if not (isinstance(node, ast.Call)
                and terminal_name(node.func) == "fire" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        if name in env.failpoint_doc_names:
            continue
        if ctx.allowed(node.lineno, "failpoint"):
            continue
        out.append(Violation(
            ctx.path, node.lineno, "R6", "failpoint-hygiene",
            f"failpoint {name!r} fires here but is missing from the "
            f"reference table in {FAILPOINT_DOC} — add a table row or "
            "annotate `# pilint: allow-failpoint(reason)`",
        ))
    return out


def failpoint_orphan_violations(env: RepoEnv) -> List[Violation]:
    """R6b (repo-level, emitted by the runner after per-file rules): every
    failpoint name a test activates must have a fire() site — a typo'd
    spec never fires, silently turning a fault test into a no-op."""
    out: List[Violation] = []
    for path, line, name in env.failpoint_spec_sites:
        if name not in env.failpoint_fire_sites:
            out.append(Violation(
                path, line, "R6", "failpoint-hygiene",
                f"activation spec names failpoint {name!r} but no "
                "failpoints.fire() site carries that name — the spec "
                "never fires and this fault test is a no-op; fix the "
                "name or annotate `# pilint: allow-failpoint(reason)`",
            ))
    return out


# --------------------------------------------------------------------------
# R7: span-name hygiene


# The recorder surface (pilosa_tpu/obs/): obs.span()/obs_span() open a
# stage span, obs.record()/obs_record()/trace.record() append a
# pre-measured one. Only CONSTANT first-arg names are checked — dynamic
# names (the f-string `remote:<peer>` hops) can't be validated statically
# and are documented in the table for humans, not the linter.
_SPAN_CALL_FUNCS = {"span", "obs_span", "record", "obs_record"}
# Test-side assertion helpers whose span-name argument R7b validates:
# a trace-shaped assertion naming a span nothing records is a no-op test.
_SPAN_ASSERT_FUNCS = {"assert_span", "find_span", "find_spans"}
_SPAN_NAME = r"[a-z][a-z0-9_.:<>-]*"


def parse_span_docs(text: str) -> Set[str]:
    """Span names from the reference table in docs/observability.md:
    table rows (lines starting with `|`) inside a `## ... span ...`
    section whose first cell is a backticked name."""
    names: Set[str] = set()
    in_section = False
    for line in text.splitlines():
        if line.startswith("## "):
            in_section = "span" in line.lower()
            continue
        if in_section:
            m = re.match(rf"\|\s*`({_SPAN_NAME})`", line)
            if m:
                names.add(m.group(1))
    return names


def _span_call_name(node: ast.Call):
    """The constant span name of a recorder call, or None."""
    if (terminal_name(node.func) in _SPAN_CALL_FUNCS and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)):
        return node.args[0].value
    return None


def collect_span_names(tree: ast.AST) -> Set[str]:
    """Every constant span name recorded anywhere in a module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _span_call_name(node)
            if name is not None:
                out.add(name)
    return out


def collect_span_assert_sites(path: str, source: str) -> List:
    """(path, line, name) for every span name a test asserts on: constant
    string args of assert_span()/find_span() helper calls. Lines carrying
    `# pilint: allow-span(reason)` are excluded — fixture negatives use
    deliberately-bogus names."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    annotations, _ = parse_annotations(path, source)
    ctx = FileContext(path=path, source=source, tree=tree,
                      annotations=annotations)
    out: List = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) in _SPAN_ASSERT_FUNCS):
            for arg in node.args:
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and re.fullmatch(_SPAN_NAME, arg.value)
                        and not ctx.allowed(node.lineno, "span")):
                    out.append((path, node.lineno, arg.value))
    return out


def rule_span_hygiene(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R7a: every constant span name passed to the recorder in
    pilosa_tpu/ must appear in docs/observability.md's span reference
    table — the table is how operators (and the trace-shaped tests)
    discover stage names, and an undocumented span is one nobody will
    filter or alert on."""
    if not ctx.path.startswith("pilosa_tpu/") or not env.span_docs_loaded:
        return []
    out: List[Violation] = []
    for node in ctx.nodes():
        if not isinstance(node, ast.Call):
            continue
        name = _span_call_name(node)
        if name is None or name in env.span_doc_names:
            continue
        if ctx.allowed(node.lineno, "span"):
            continue
        out.append(Violation(
            ctx.path, node.lineno, "R7", "span-hygiene",
            f"span {name!r} is recorded here but missing from the span "
            f"reference table in {SPAN_DOC} — add a table row or annotate "
            "`# pilint: allow-span(reason)`",
        ))
    return out


def span_orphan_violations(env: RepoEnv) -> List[Violation]:
    """R7b (repo-level, emitted by the runner after per-file rules): every
    span name a test asserts on must have a recording site — a typo'd
    assertion waits on a span that never records, silently turning a
    trace-shaped test into a no-op."""
    out: List[Violation] = []
    for path, line, name in env.span_assert_sites:
        if name not in env.span_record_sites:
            out.append(Violation(
                path, line, "R7", "span-hygiene",
                f"test asserts on span {name!r} but no recording site "
                "carries that name — the assertion can never match; fix "
                "the name or annotate `# pilint: allow-span(reason)`",
            ))
    return out


# --------------------------------------------------------------------------
# R5: mutation-epoch audit (core/ only)


_STORAGE_MUTATORS = {"add", "remove", "add_many", "remove_many",
                     "add_sorted", "remove_sorted", "read_from"}
_BUMP_CALLS = {"bump", "_invalidate_row", "_invalidate_bulk", "_journal_reset"}


def _method_facts(fn: ast.FunctionDef):
    """(mutates: [lineno], bumps: bool, callees: set[str]) for one method."""
    mutates: List[int] = []
    bumps = False
    callees: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                base = terminal_name(f.value)
                if f.attr in _STORAGE_MUTATORS and base == "storage":
                    mutates.append(node.lineno)
                if f.attr in _BUMP_CALLS:
                    bumps = True
                if isinstance(f.value, ast.Name) and f.value.id == "self":
                    callees.add(f.attr)
            elif isinstance(f, ast.Name):
                if f.id == "replay_ops":
                    mutates.append(node.lineno)
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == "generation":
                    bumps = True
    return mutates, bumps, callees


def rule_mutation_epoch(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R5, on the shared call graph since pilint v2: the bump-reach walk
    uses the same class/method tables and config-bounded depth limit as
    the other interprocedural rules instead of its own ad-hoc recursion
    (facts still walk full method bodies, nested defs included — a bump
    inside a worker closure the method spawns still counts)."""
    if "core/" not in ctx.path:
        return []
    out: List[Violation] = []
    graph = ctx.graph()
    depth_limit = ctx.depth or DEFAULT_DEPTH
    for cls, methods in graph.methods_of.items():
        nodes = {name: graph.functions[qual].node
                 for name, qual in methods.items()}
        facts = {name: _method_facts(fn) for name, fn in nodes.items()}

        def reaches_bump(name: str, depth: int, seen: Set[str]) -> bool:
            if name in seen or name not in facts or depth > depth_limit:
                return False
            seen.add(name)
            _, bumps, callees = facts[name]
            if bumps:
                return True
            return any(reaches_bump(c, depth + 1, seen) for c in callees)

        for name, fn in nodes.items():
            mutates, _, _ = facts[name]
            if not mutates:
                continue
            if reaches_bump(name, 0, set()):
                continue
            if ctx.allowed(fn.lineno, "mutation"):
                continue
            out.append(Violation(
                ctx.path, fn.lineno, "R5", "mutation-epoch-audit",
                f"`{name}` mutates bitmap storage (line {mutates[0]}) but "
                "never reaches a generation/epoch bump — stale device "
                "caches would serve the old plane; bump or annotate "
                "`# pilint: allow-mutation(reason)`",
            ))
    return out


# --------------------------------------------------------------------------
# R8: guarded device materialization (parallel/engine.py, collective.py)


# Files the rule judges: the two modules that dispatch device programs.
R8_FILES = ("pilosa_tpu/parallel/engine.py",
            "pilosa_tpu/parallel/collective.py")
# Calls that return a compiled device program; calling the returned
# object produces an UNMATERIALIZED device value (async dispatch).
_R8_PROGRAM_GETTERS = {"_fn", "_fn_build", "_fn_probe", "jit"}
# The dispatch guards: a thunk passed to one of these runs under the
# fault ladder (classification, breakers, OOM backpressure + retry).
_R8_GUARD_CALLS = {"_device_call", "_oom_guard", "_watchdogged"}
# Ladder roots: methods whose whole body IS the guarded region — the
# collective runner thread executes _enter under _lead's breaker-feeding
# try, so helpers reached only from it materialize inside the ladder.
_R8_GUARD_ROOTS = {"_enter"}
# Calls that force a device result to the host (where a real device
# fault surfaces under jax's async dispatch).
_R8_FORCING_FUNCS = {"asarray", "device_get"}
_R8_FORCING_METHOD = "block_until_ready"
# Wrappers that force a thunk's return value, making the guard's result
# safe to touch outside it.
_R8_LOCAL_FORCERS = _R8_FORCING_FUNCS | {"int", "float", "bool", "tolist",
                                         "item", "array"}


class _R8Analysis:
    """Per-module taint + guard-domination analysis for R8.

    Taint = "may be an unmaterialized device value": calls of device
    programs, values returned un-forced through the guard or through a
    tainted-returning function, and anything derived from those
    (unpacking, slicing, dtype casts). Forcing taint (np.asarray /
    device_get / .block_until_ready) must happen inside the guard —
    outside it, jax's async dispatch surfaces a real device fault as a
    raw XlaRuntimeError that bypasses classification, the breakers, and
    the executor's ladder entirely (the PR 9 round-5 bug class)."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.graph: ModuleGraph = ctx.graph()
        self.parents = ctx.parents()
        self.node_fn = {fn.node: fn for fn in self.graph.functions.values()}
        self.program_attrs: Set[str] = set()  # self.X = jax.jit(...)
        self.tainted_returning: Set[str] = set()
        self.local_taint: Dict[str, Set[str]] = {}
        self._pv_cache: Dict[str, Set[str]] = {}
        self.guard_thunks: Set[ast.AST] = set()
        self._collect_guard_thunks()
        self._collect_program_attrs()
        self._taint_fixpoint()
        self.dominated = self._guard_dominated()

    # ----------------------------------------------------- guard geometry

    def _collect_guard_thunks(self) -> None:
        """Lambdas and named local defs passed as arguments to a guard
        call run under the ladder."""
        for fn in self.graph.functions.values():
            for site in fn.calls:
                if terminal_name(site.node.func) not in _R8_GUARD_CALLS:
                    continue
                for arg in site.node.args:
                    if isinstance(arg, ast.Lambda):
                        self.guard_thunks.add(arg)
                    elif isinstance(arg, ast.Name):
                        qual = fn.nested.get(arg.id)
                        if qual is not None:
                            self.guard_thunks.add(
                                self.graph.functions[qual].node)

    def _enclosing_context(self, node: ast.AST):
        """Walk parents from `node`: ("thunk", None) when a guard thunk
        encloses it first, else ("fn", FuncNode) for the innermost named
        function, else ("module", None)."""
        cur = self.parents.get(node)
        while cur is not None:
            if cur in self.guard_thunks:
                return "thunk", None
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return "fn", self.node_fn.get(cur)
            cur = self.parents.get(cur)
        return "module", None

    def _enclosing_named_fn(self, node: ast.AST):
        """The innermost NAMED function enclosing `node` (lambdas are
        skipped — their names resolve in the enclosing scope)."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self.node_fn.get(cur)
            cur = self.parents.get(cur)
        return None

    def _guard_dominated(self) -> Set[str]:
        """Functions whose EVERY in-module call site sits in guarded
        context (a guard thunk, a guard root, or another dominated
        function) — their bodies execute under the ladder. Functions
        with no visible call site (public API) are never dominated.

        Call sites are collected from the FULL tree (lambda bodies
        included — FuncNode.calls prunes them, but a helper invoked
        only from inside guard thunks is exactly the dominated case)."""
        sites: Dict[str, List[ast.AST]] = {}
        for node in self.ctx.nodes():
            if not isinstance(node, ast.Call):
                continue
            fn = self._enclosing_named_fn(node)
            if fn is None:
                continue
            callee = self.graph.resolve(fn, node)
            if callee is not None:
                sites.setdefault(callee, []).append(node)
        dominated: Set[str] = set()

        def guarded_site(node: ast.AST) -> bool:
            kind, fnode = self._enclosing_context(node)
            if kind == "thunk":
                return True
            return (kind == "fn" and fnode is not None
                    and (fnode.name in _R8_GUARD_ROOTS
                         or fnode.qualname in dominated))

        changed = True
        while changed:
            changed = False
            for qual, call_nodes in sites.items():
                if qual in dominated:
                    continue
                if all(guarded_site(n) for n in call_nodes):
                    dominated.add(qual)
                    changed = True
        return dominated

    def in_guard_context(self, node: ast.AST) -> bool:
        kind, fnode = self._enclosing_context(node)
        if kind == "thunk":
            return True
        return (kind == "fn" and fnode is not None
                and (fnode.name in _R8_GUARD_ROOTS
                     or fnode.qualname in self.dominated))

    # -------------------------------------------------------------- taint

    def _collect_program_attrs(self) -> None:
        for fn in self.graph.functions.values():
            for node in own_body_walk(fn.node):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and terminal_name(node.value.func)
                        in _R8_PROGRAM_GETTERS):
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and terminal_name(t.value) == "self"):
                            self.program_attrs.add(t.attr)

    def _taint_env(self, fn) -> Set[str]:
        """A nested def/lambda closes over its ancestors' locals."""
        names: Set[str] = set()
        cur = fn
        while cur is not None:
            names |= self.local_taint.get(cur.qualname, set())
            cur = (self.graph.functions.get(cur.parent)
                   if cur.parent else None)
        return names

    def _program_vars(self, fn) -> Set[str]:
        # Memoized per qualname: program-var bindings derive from
        # program-getter Assigns only, never from taint, so the set is
        # invariant across the fixpoint — recomputing it per tainted()
        # query was the dominant redundant cost on collective.py.
        cached = self._pv_cache.get(fn.qualname)
        if cached is not None:
            return cached
        out: Set[str] = set()
        for node in own_body_walk(fn.node):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and terminal_name(node.value.func)
                    in _R8_PROGRAM_GETTERS):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        parent = (self.graph.functions.get(fn.parent)
                  if fn.parent else None)
        if parent is not None:
            out |= self._program_vars(parent)
        self._pv_cache[fn.qualname] = out
        return out

    def tainted(self, expr: ast.AST, fn) -> bool:
        """May `expr` (evaluated inside function `fn`) be an
        unmaterialized device value?"""
        taint = self._taint_env(fn)
        progs = self._program_vars(fn)

        def walk(e: ast.AST) -> bool:
            if isinstance(e, ast.Name):
                return e.id in taint
            if isinstance(e, ast.Subscript):
                return walk(e.value)
            if isinstance(e, ast.Starred):
                return walk(e.value)
            if isinstance(e, ast.Tuple) or isinstance(e, ast.List):
                return any(walk(x) for x in e.elts)
            if isinstance(e, ast.BinOp):
                return walk(e.left) or walk(e.right)
            if isinstance(e, ast.IfExp):
                return walk(e.body) or walk(e.orelse)
            if isinstance(e, ast.Call):
                f = e.func
                # program(...) — a dispatch: the canonical taint source
                if isinstance(f, ast.Name) and f.id in progs:
                    return True
                if (isinstance(f, ast.Attribute)
                        and terminal_name(f.value) == "self"
                        and f.attr in self.program_attrs):
                    return True
                # method chains on a tainted value: .astype/.reshape keep
                # device-ness; .block_until_ready() forces it
                if isinstance(f, ast.Attribute) and walk(f.value):
                    return f.attr != _R8_FORCING_METHOD
                # guard call whose thunk returns taint un-forced
                if terminal_name(f) in _R8_GUARD_CALLS:
                    return self._thunk_returns_taint(e, fn)
                # call of a tainted-returning function in this module
                callee = self.graph.resolve(fn, e) if fn is not None else None
                if callee is not None and callee in self.tainted_returning:
                    return True
                return False
            return False

        return walk(expr)

    def _thunk_returns_taint(self, guard_call: ast.Call, fn) -> bool:
        for arg in guard_call.args:
            if isinstance(arg, ast.Lambda):
                return self._forces(arg.body) is False and self.tainted(
                    arg.body, fn)
            if isinstance(arg, ast.Name) and fn is not None:
                qual = fn.nested.get(arg.id)
                if qual is None:
                    continue
                thunk = self.graph.functions[qual]
                for node in own_body_walk(thunk.node):
                    if (isinstance(node, ast.Return) and node.value is not None
                            and not self._forces(node.value)
                            and self.tainted(node.value, thunk)):
                        return True
                return False
        return False

    @staticmethod
    def _forces(expr: ast.AST) -> bool:
        """Does the outermost operation of `expr` force to host? (int(),
        np.asarray(), .block_until_ready(), tuples of those...)"""
        if isinstance(expr, ast.Subscript):
            return _R8Analysis._forces(expr.value)
        if isinstance(expr, ast.Tuple):
            return all(_R8Analysis._forces(e) for e in expr.elts)
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Attribute) and f.attr == _R8_FORCING_METHOD:
                return True
            return terminal_name(f) in _R8_LOCAL_FORCERS
        return False

    def _taint_fixpoint(self) -> None:
        """Iterate local-assignment taint + tainted-returning functions
        to a fixpoint (bounded by function count; in practice 2-3
        rounds). Taint only ever grows, so this terminates."""
        for _ in range(len(self.graph.functions) + 1):
            changed = False
            for fn in self.graph.functions.values():
                local = self.local_taint.setdefault(fn.qualname, set())
                for node in own_body_walk(fn.node):
                    if isinstance(node, ast.Assign):
                        if not self.tainted(node.value, fn):
                            continue
                        for t in node.targets:
                            for name in _target_names(t):
                                if name not in local:
                                    local.add(name)
                                    changed = True
                    elif (isinstance(node, ast.Return)
                          and node.value is not None
                          and fn.qualname not in self.tainted_returning
                          and self.tainted(node.value, fn)):
                        self.tainted_returning.add(fn.qualname)
                        changed = True
            if not changed:
                return


def _target_names(t: ast.AST) -> List[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    return []


def rule_guarded_materialization(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R8: in the dispatch modules, forcing a device value to the host
    (np.asarray / jax.device_get / .block_until_ready) must happen
    inside the `_device_call`/`_oom_guard` guard or a ladder-dominated
    helper. jax dispatches asynchronously, so a device fault surfaces at
    MATERIALIZATION — un-guarded, it escapes as a raw XlaRuntimeError
    that bypasses classification, the breakers, and the executor's
    fallback ladder (the PR 9 round-5 review bug, re-fixed here as a
    machine-checked invariant). Escape: `# pilint: allow-materialize`."""
    if ctx.path not in R8_FILES:
        return []
    out: List[Violation] = []
    a = _R8Analysis(ctx)
    for fn in a.graph.functions.values():
        for node in own_body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            forced_expr = None
            label = None
            if (terminal_name(f) in _R8_FORCING_FUNCS and node.args):
                forced_expr, label = node.args[0], (dotted_name(f)
                                                    or terminal_name(f))
            elif (isinstance(f, ast.Attribute)
                  and f.attr == _R8_FORCING_METHOD):
                forced_expr, label = f.value, _R8_FORCING_METHOD
            if forced_expr is None or not a.tainted(forced_expr, fn):
                continue
            if a.in_guard_context(node):
                continue
            if ctx.allowed(node.lineno, "materialize"):
                continue
            out.append(Violation(
                ctx.path, node.lineno, "R8", "guarded-materialization",
                f"`{label}` forces a device dispatch result outside the "
                "_device_call/ladder guard — with async dispatch a device "
                "fault surfaces HERE as a raw XlaRuntimeError, bypassing "
                "classification, the breakers, and the executor's ladder; "
                "materialize inside the guard thunk or annotate "
                "`# pilint: allow-materialize(reason)`",
            ))
    return out


# --------------------------------------------------------------------------
# R9: probe-claim hygiene (parallel/device_health.py, cluster/health.py)


R9_FILES = ("pilosa_tpu/parallel/device_health.py",
            "pilosa_tpu/cluster/health.py")
_R9_PROBE_ATTRS = {"probe_at"}
_R9_STATE_ATTRS = {"probe_at", "opened_at", "state"}


def _assigns_probe_claim(fn_node: ast.AST) -> bool:
    """Does this method write probe-claim state directly? (The claiming
    primitive — `_gate_locked` sets `b.probe_at` when it hands out the
    half-open probe.)"""
    for node in ast.walk(fn_node):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and t.attr in _R9_PROBE_ATTRS:
                return True
    return False


def _side_effect_free_check(fn_node: ast.AST) -> bool:
    """A `_due_locked`-style gate check: reads breaker state, writes
    nothing (no attribute/subscript stores anywhere in the body)."""
    reads_state = False
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    return False
        if (isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and node.attr in _R9_STATE_ATTRS):
            reads_state = True
    return reads_state


def rule_probe_claim_hygiene(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R9: a method that claims half-open probes for MORE THAN ONE
    breaker must run a side-effect-free `_due_locked`-style pass over
    every breaker BEFORE the first claim. Claiming the plane's probe and
    then short-circuiting on a still-backed-off sig/slice orphans the
    probe, which expires as a FAILURE and doubles the backoff from
    short-circuits alone — the bug fixed independently in
    DevicePlaneHealth.plan and CollectivePlaneHealth.allow, encoded here
    so the next breaker doesn't re-ship it. Escape: `# pilint:
    allow-probe(reason)`."""
    if ctx.path not in R9_FILES:
        return []
    out: List[Violation] = []
    graph = ctx.graph()
    for cls, methods in graph.methods_of.items():
        nodes = {name: graph.functions[qual].node
                 for name, qual in methods.items()}
        mutators = {name for name, fn in nodes.items()
                    if _assigns_probe_claim(fn)}
        checks = {name for name, fn in nodes.items()
                  if name not in mutators and _side_effect_free_check(fn)}
        if not mutators:
            continue
        mutator_quals = {f"{cls}.{m}" for m in mutators}
        check_quals = {f"{cls}.{c}" for c in checks}
        for name, qual in methods.items():
            if name in mutators:
                continue
            fn = graph.functions[qual]
            claim_lines = sorted(site.lineno for site in fn.calls
                                 if site.callee in mutator_quals)
            if len(claim_lines) < 2:
                continue
            check_lines = [site.lineno for site in fn.calls
                           if site.callee in check_quals]
            if any(line < claim_lines[0] for line in check_lines):
                continue
            if ctx.allowed(fn.node.lineno, "probe") or ctx.allowed(
                    claim_lines[0], "probe"):
                continue
            out.append(Violation(
                ctx.path, claim_lines[0], "R9", "probe-claim-hygiene",
                f"`{name}` claims half-open probes for {len(claim_lines)} "
                "breakers with no side-effect-free `_due_locked`-style "
                "pass before the first claim — a later short-circuit "
                "orphans the claimed probe, which expires as a failure "
                "and doubles the backoff from short-circuits alone; "
                "check every breaker's due-ness first or annotate "
                "`# pilint: allow-probe(reason)`",
            ))
    return out


# --------------------------------------------------------------------------
# R10: None-guarded stats (the PR 12 crash class)


_R10_METHODS = {"count", "timing"}
_R10_BASES = {"stats", "_stats"}


def _stats_chain(call: ast.Call) -> Optional[str]:
    """'self.holder.stats' for `self.holder.stats.count(...)` when the
    receiver chain ends in a stats attribute, else None."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in _R10_METHODS):
        return None
    if terminal_name(f.value) not in _R10_BASES:
        return None
    return dotted_name(f.value)


def _test_asserts_chain(test: ast.AST, chain: str) -> bool:
    """Does `test` (an if/while/ternary condition) assert `chain` is
    truthy? Handles `chain`, `chain is not None`, and `and` chains."""
    if dotted_name(test) == chain:
        return True
    if (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.IsNot)
            and dotted_name(test.left) == chain
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None):
        return True
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        return any(_test_asserts_chain(v, chain) for v in test.values)
    return False


def _never_none_attr(cls: ast.ClassDef, attr: str) -> bool:
    """True when every assignment to `self.<attr>` in the class provably
    yields a non-None value: a constructor call, or the `x or Fallback()`
    coalescing idiom (Server.stats = stats or InMemoryStatsClient()).
    One bare-name assignment (could be None) makes the attr nullable."""
    found = False
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for t in targets:
            if not (isinstance(t, ast.Attribute) and t.attr == attr
                    and terminal_name(t.value) == "self"):
                continue
            found = True
            if isinstance(value, ast.Call):
                continue
            if (isinstance(value, ast.BoolOp)
                    and isinstance(value.op, ast.Or)
                    and isinstance(value.values[-1], ast.Call)):
                continue
            return False
    return found


def rule_none_guarded_stats(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R10: a direct `<holder>.stats.count(...)` / `.timing(...)` call
    must be dominated by a None-check of the SAME stats chain — library
    embedders run `Holder(None)` with no stats client, and the PR 12
    review rounds caught ladder counters crashing exactly those degraded
    paths. Route through a `_count_stat`-style guard helper (whose body
    is the dominating check) or guard inline. A `self.stats` whose class
    coalesces it non-None at construction (`stats or InMemoryStats()`)
    is exempt — that holder is never stats-less. Escape: `# pilint:
    allow-stat(reason)`."""
    if not ctx.path.startswith("pilosa_tpu/"):
        return []
    out: List[Violation] = []
    parents = ctx.parents()
    nonnull_cache: Dict[Tuple[int, str], bool] = {}
    for node in ctx.nodes():
        if not isinstance(node, ast.Call):
            continue
        chain = _stats_chain(node)
        if chain is None:
            continue
        parts = chain.split(".")
        if len(parts) == 2 and parts[0] == "self":
            cls = parents.get(node)
            while cls is not None and not isinstance(cls, ast.ClassDef):
                cls = parents.get(cls)
            if cls is not None:
                key = (id(cls), parts[1])
                if key not in nonnull_cache:
                    nonnull_cache[key] = _never_none_attr(cls, parts[1])
                if nonnull_cache[key]:
                    continue
        # Dominating guard: any enclosing if/ternary/`and` asserting the
        # chain, with the call on the truthy side.
        guarded = False
        child: ast.AST = node
        cur = parents.get(node)
        while cur is not None and not guarded:
            if isinstance(cur, ast.If) and _test_asserts_chain(cur.test, chain):
                guarded = child not in getattr(cur, "orelse", [])
                if guarded:
                    break
            if isinstance(cur, ast.IfExp) and _test_asserts_chain(cur.test, chain):
                guarded = child is not cur.orelse
                if guarded:
                    break
            if (isinstance(cur, ast.BoolOp) and isinstance(cur.op, ast.And)
                    and any(_test_asserts_chain(v, chain)
                            for v in cur.values[:-1])):
                guarded = True
                break
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Early-return guard at this function's top level:
                # `if chain is None: return` before the call.
                for stmt in cur.body:
                    if stmt.lineno >= node.lineno:
                        break
                    if (isinstance(stmt, ast.If)
                            and _is_none_bailout(stmt, chain)):
                        guarded = True
                        break
                break
            child, cur = cur, parents.get(cur)
        if guarded:
            continue
        if ctx.allowed(node.lineno, "stat"):
            continue
        out.append(Violation(
            ctx.path, node.lineno, "R10", "none-guarded-stats",
            f"direct `{chain}.{node.func.attr}(...)` with no None-guard — "
            "stats-less holders (Holder(None), library embedders) crash "
            "here, and a degraded-path counter must never be what breaks "
            "the degraded path; route through a `_count_stat`-style "
            "guard or annotate `# pilint: allow-stat(reason)`",
        ))
    return out


def _is_none_bailout(stmt: ast.If, chain: str) -> bool:
    test = stmt.test
    is_none = (isinstance(test, ast.Compare) and len(test.ops) == 1
               and isinstance(test.ops[0], ast.Is)
               and dotted_name(test.left) == chain
               and isinstance(test.comparators[0], ast.Constant)
               and test.comparators[0].value is None)
    is_not_truthy = (isinstance(test, ast.UnaryOp)
                     and isinstance(test.op, ast.Not)
                     and dotted_name(test.operand) == chain)
    if not (is_none or is_not_truthy):
        return False
    return bool(stmt.body) and isinstance(
        stmt.body[-1], (ast.Return, ast.Raise, ast.Continue))


# --------------------------------------------------------------------------
# R11: config-surface completeness


# section class -> (Config attr/section name, flag prefix, env prefix,
# reference doc). A field of one of these dataclasses must be reachable
# from every operator surface: the TOML parser (_apply_dict, checked as
# the section-scoped `self.<section>.<field>` store) AND dump (to_toml,
# checked inside the section's own `[...]` block), a PILOSA_TPU_* env
# spelling, the CLI flag (mapping key in config.py + --flag in cli.py),
# and its subsystem doc — the R6/R7 corpus pattern applied to the config
# plane, so a knob an operator can't discover or round-trip is caught
# before the operator is.
R11_SECTIONS: Dict[str, Tuple[str, str, str, str]] = {
    "SchedulerConfig": ("scheduler", "sched", "SCHED", "docs/scheduler.md"),
    "StorageConfig": ("storage", "storage", "STORAGE", "docs/durability.md"),
    "IngestConfig": ("ingest", "ingest", "INGEST", "docs/ingest.md"),
    "EngineConfig": ("engine", "engine", "ENGINE", "docs/engine-caches.md"),
    "CollectiveConfig": ("collective", "collective", "COLLECTIVE",
                         "docs/multichip.md"),
    "TierConfig": ("tier", "tier", "TIER", "docs/tiered-storage.md"),
    "ResilienceConfig": ("resilience", "resilience", "RESILIENCE",
                         "docs/fault-tolerance.md"),
    "RebalanceConfig": ("rebalance", "rebalance", "REBALANCE",
                        "docs/rebalance.md"),
    "ReplicationConfig": ("replication", "replication", "REPLICATION",
                          "docs/durability.md"),
    "ObsConfig": ("obs", "obs", "OBS", "docs/observability.md"),
    "CdcConfig": ("cdc", "cdc", "CDC", "docs/cdc.md"),
    "GeoConfig": ("geo", "geo", "GEO", "docs/geo-replication.md"),
    "QosConfig": ("qos", "qos", "QOS", "docs/scheduler.md"),
    "TransportConfig": ("transport", "transport", "TRANSPORT",
                        "docs/transport.md"),
    "AutoscaleConfig": ("autoscale", "autoscale", "AUTOSCALE",
                        "docs/rebalance.md"),
}
CONFIG_FILE = "pilosa_tpu/config.py"
CLI_FILE = "pilosa_tpu/cli.py"


def collect_string_constants(tree: ast.AST) -> Set[str]:
    """Every string constant in a module, f-string constant parts
    included (to_toml builds its dump lines as f-strings)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
    return out


def _is_dataclass(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        name = terminal_name(dec.func if isinstance(dec, ast.Call) else dec)
        if name == "dataclass":
            return True
    return False


def rule_config_surface(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R11: every field of a section `*Config` dataclass is reachable
    from the whole operator surface. Missing surfaces are listed in one
    finding per field. Escape: `# pilint: allow-config(reason)` on the
    field line (for deliberately internal knobs)."""
    if not env.config_surface_loaded:
        return []
    out: List[Violation] = []
    for node in ctx.nodes():
        if not (isinstance(node, ast.ClassDef) and node.name in R11_SECTIONS
                and _is_dataclass(node)):
            continue
        section, flag_prefix, env_prefix, doc_path = R11_SECTIONS[node.name]
        doc_text = env.config_docs.get(doc_path)
        for stmt in node.body:
            if not (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                continue
            fname = stmt.target.id
            if fname.startswith("_"):
                continue
            toml_key = fname.replace("_", "-")
            missing: List[str] = []
            # Section-scoped: a key another section also spells must not
            # mask this one's missing parse line / dump row.
            if f"self.{section}.{fname}" not in env.config_set_attrs:
                missing.append(
                    f"TOML parser (_apply_dict: no "
                    f"`self.{section}.{fname} = ...` store)")
            dump_prefix = f"{toml_key} = "
            if not any(c.startswith(dump_prefix)
                       for c in env.config_dump_rows.get(section, ())):
                missing.append(
                    f"TOML dump (no {toml_key!r} row in the [{section}] "
                    "block of to_toml)")
            env_name = f"{env_prefix}_{fname.upper()}"
            if env_name not in env.config_constants:
                missing.append(f"env spelling (PILOSA_TPU_{env_name})")
            flag_key = f"{flag_prefix}_{fname}"
            if flag_key not in env.config_constants:
                missing.append(f"flag mapping (_apply_flags {flag_key!r})")
            cli_flag = f"--{flag_prefix}-{toml_key}"
            if cli_flag not in env.cli_constants:
                missing.append(f"CLI flag ({cli_flag})")
            if doc_text is not None and not re.search(
                    rf"(?<![a-z0-9-]){re.escape(toml_key)}(?![a-z0-9-])",
                    doc_text):
                missing.append(f"docs ({doc_path})")
            if not missing:
                continue
            if ctx.allowed(stmt.lineno, "config"):
                continue
            out.append(Violation(
                ctx.path, stmt.lineno, "R11", "config-surface",
                f"[{node.name}] field `{fname}` is unreachable from: "
                + "; ".join(missing)
                + " — an operator can't discover or set what isn't on "
                "every surface; wire it through or annotate "
                "`# pilint: allow-config(reason)`",
            ))
    return out


ALL_RULES = (
    ("R1", rule_swallow),
    ("R2", rule_jax_free),
    ("R3", rule_blocking_under_lock),
    ("R4", rule_counter_hygiene),
    ("R5", rule_mutation_epoch),
    ("R6", rule_failpoint_hygiene),
    ("R7", rule_span_hygiene),
    ("R8", rule_guarded_materialization),
    ("R9", rule_probe_claim_hygiene),
    ("R10", rule_none_guarded_stats),
    ("R11", rule_config_surface),
)
