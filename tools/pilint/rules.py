"""The five pilint rules. Each rule is a function(ctx, env) -> [Violation].

`env` is a RepoEnv carrying the cross-file facts some rules need (R4's
/debug/vars wiring corpus). Rules are pure AST walks — no imports of the
linted code, so a file with a missing optional dependency still lints.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from .core import (FileContext, Violation, dotted_name, parse_annotations,
                   terminal_name)

# --------------------------------------------------------------------------
# cross-file environment


@dataclass
class RepoEnv:
    """Facts gathered once per run, consumed by individual rules.

    wired_literals: every string literal in the /debug/vars wiring files
        (server/handler.py, diagnostics.py) — a counter key appearing
        there is observable by an operator.
    stats_wholesale: True when handler.py dumps `stats.snapshot()`
        wholesale into /debug/vars, which makes every `stats.count(name)`
        counter observable without listing its name.
    failpoint_doc_names: failpoint names listed in docs/durability.md's
        reference table (R6: every fire() site must appear there).
    failpoint_docs_loaded: True when the docs file was actually read —
        R6's fire-site half no-ops otherwise, so fixture runs that lint
        a lone snippet without wiring the docs don't false-positive.
    failpoint_fire_sites: every name passed to failpoints.fire() across
        pilosa_tpu/ (R6: a test activation spec must name one of these —
        a typo'd spec silently turns a fault test into a no-op).
    failpoint_spec_sites: (path, line, name) of every failpoint name a
        test activates/configures, with allow-failpoint-annotated lines
        already filtered out.
    span_doc_names: span names listed in docs/observability.md's span
        reference table (R7: every recorder span name must appear there).
    span_docs_loaded: True when that doc was actually read — R7's
        recording-site half no-ops otherwise (fixture runs).
    span_record_sites: every constant span name passed to a recorder call
        across pilosa_tpu/ (R7: a name a test asserts on must name one of
        these — a typo'd assertion tests a span that never records).
    span_assert_sites: (path, line, name) of every span name a test
        asserts on (assert_span/find_span helper calls), allow-span-
        annotated lines already filtered out.
    """

    wired_literals: Set[str] = field(default_factory=set)
    stats_wholesale: bool = False
    failpoint_doc_names: Set[str] = field(default_factory=set)
    failpoint_docs_loaded: bool = False
    failpoint_fire_sites: Set[str] = field(default_factory=set)
    failpoint_spec_sites: List = field(default_factory=list)
    span_doc_names: Set[str] = field(default_factory=set)
    span_docs_loaded: bool = False
    span_record_sites: Set[str] = field(default_factory=set)
    span_assert_sites: List = field(default_factory=list)


WIRING_FILES = ("pilosa_tpu/server/handler.py", "pilosa_tpu/diagnostics.py")
# R6's reference table lives in the durability doc (the failpoint section).
FAILPOINT_DOC = "docs/durability.md"
# R7's reference table lives in the observability doc (the span section).
SPAN_DOC = "docs/observability.md"


def build_env(sources: Dict[str, str]) -> RepoEnv:
    env = RepoEnv()
    for rel in WIRING_FILES:
        src = sources.get(rel)
        if src is None:
            continue
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                env.wired_literals.add(node.value)
            if (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "snapshot"
                    and isinstance(node.func, ast.Attribute)
                    and terminal_name(node.func.value) == "stats"):
                env.stats_wholesale = True
    return env


# --------------------------------------------------------------------------
# R1: no swallowed exceptions


_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}
_BROAD = {"Exception", "BaseException"}


def _is_broad(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:  # bare except:
        return True
    if isinstance(t, ast.Tuple):
        return any(terminal_name(e) in _BROAD for e in t.elts)
    return terminal_name(t) in _BROAD


def _body_handles(h: ast.ExceptHandler) -> bool:
    """True when the handler body re-raises, logs, counts, or captures the
    exception for later use — i.e. the failure leaves a trace."""
    exc_name = h.name
    for node in ast.walk(ast.Module(body=list(h.body), type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute):
                base = terminal_name(fn.value) or ""
                # self.logger.error(...), logging.warning(...), log.info(...)
                if fn.attr in _LOG_METHODS and "log" in base.lower():
                    return True
                # stats.count("X", n) / self._stats.add_pending(...)
                if fn.attr == "count":
                    return True
        # counters["x"] += 1 / self.quarantined_reads += 1
        if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
            tgt = node.target
            if isinstance(tgt, ast.Subscript) or isinstance(tgt, ast.Attribute):
                return True
        # `except ... as e` whose body USES e (stores it, appends it,
        # formats it into a result): the error is captured, not dropped.
        if (exc_name and isinstance(node, ast.Name)
                and node.id == exc_name and isinstance(node.ctx, ast.Load)):
            return True
    return False


def _try_body_imports(handler: ast.ExceptHandler, tree: ast.AST) -> bool:
    """True when `handler` belongs to a Try whose body is import work."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Try) and handler in node.handlers:
            return any(isinstance(s, (ast.Import, ast.ImportFrom))
                       for s in node.body)
    return False


def rule_swallow(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler) or not _is_broad(node):
            continue
        if _try_body_imports(node, ctx.tree):
            # No annotation escape: a broad guard around an import hides
            # typos inside the guarded module forever. Catch ImportError.
            out.append(Violation(
                ctx.path, node.lineno, "R1", "swallowed-exceptions",
                "broad except around an import guard — catch ImportError "
                "(a typo inside the imported module currently vanishes)",
            ))
            continue
        if _body_handles(node):
            continue
        if ctx.allowed(node.lineno, "swallow"):
            continue
        out.append(Violation(
            ctx.path, node.lineno, "R1", "swallowed-exceptions",
            "broad except swallows the error: log it, count it into "
            "/debug/vars, re-raise, narrow the type, or annotate "
            "`# pilint: allow-swallow(reason)`",
        ))
    return out


# --------------------------------------------------------------------------
# R2: jax-free zones


# Modules the configuration surface imports at CLI startup; they must
# stay importable on a box with no jax (docs/static-analysis.md).
JAX_FREE_ZONES = (
    "pilosa_tpu/config.py",
    "pilosa_tpu/ingest.py",
    "pilosa_tpu/tier/__init__.py",
    "pilosa_tpu/parallel/__init__.py",
    "pilosa_tpu/sched/",
    "pilosa_tpu/obs/",
    "pilosa_tpu/plan/",
)


def _in_zone(path: str) -> bool:
    return any(path == z or (z.endswith("/") and path.startswith(z))
               for z in JAX_FREE_ZONES)


def rule_jax_free(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    if not _in_zone(ctx.path):
        return []
    out: List[Violation] = []

    def check(body, toplevel: bool) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # deferred to call time: allowed
            if isinstance(node, ast.If):
                test = node.test
                if terminal_name(test) == "TYPE_CHECKING":
                    # The if-body is typing-only and never executes, but an
                    # `else:` branch DOES run at import time — keep checking it.
                    check(node.orelse, toplevel)
                    continue
                check(node.body, toplevel)
                check(node.orelse, toplevel)
                continue
            if isinstance(node, (ast.Try, ast.With, ast.AsyncWith,
                                 ast.ClassDef, ast.For, ast.AsyncFor,
                                 ast.While)):
                # Every statement list of a compound statement executes at
                # import time (only def bodies defer): try/else/finally,
                # loop bodies and their else clauses included.
                check(node.body, toplevel)
                if isinstance(node, ast.Try):
                    for h in node.handlers:
                        check(h.body, toplevel)
                    check(node.orelse, toplevel)
                    check(node.finalbody, toplevel)
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    check(node.orelse, toplevel)
                continue
            names = []
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                names = [node.module]
            for n in names:
                if n == "jax" or n.startswith("jax."):
                    out.append(Violation(
                        ctx.path, node.lineno, "R2", "jax-free-zones",
                        f"module-level `import {n}` in a jax-free zone — "
                        "move it inside the function that needs it",
                    ))

    check(ctx.tree.body, True)
    return out


# --------------------------------------------------------------------------
# R3: no blocking calls under a lock


_LOCK_NAME_RE = re.compile(
    r"(?:^|_)(lock|rlock|mu|mutex|cv|cond)\d*$", re.IGNORECASE
)

# Deny-listed *direct* calls inside a `with <lock>:` block. This is a
# lexical check — calls that block transitively are the runtime lock
# checker's job (pilosa_tpu/devtools/lockcheck.py). Each entry is either
# a full dotted name or ('*', terminal_attr).
_DENY_DOTTED = {
    "time.sleep", "_time.sleep",
    "os.fsync", "os.fdatasync", "os.replace", "os.rename",
    "shutil.move", "shutil.copyfile",
    "jax.device_put",
    "socket.create_connection",
    "urllib.request.urlopen",
}
_DENY_TERMINAL = {
    # socket / HTTP client sends
    "urlopen", "getresponse", "sendall", "create_connection",
    "send_message",
    # device transfers + engine gathers (serialize off-lock: PR 5/7 rules)
    "device_put", "block_until_ready", "_gather_leaf",
    "_stacked_leaf_tensor",
    # durability syscalls regardless of the module alias
    "fsync", "fdatasync",
}


def _is_lock_name(expr: ast.AST) -> bool:
    name = terminal_name(expr)
    return bool(name and _LOCK_NAME_RE.search(name))


def _deny_match(call: ast.Call) -> Optional[str]:
    dn = dotted_name(call.func)
    if dn in _DENY_DOTTED:
        return dn
    term = terminal_name(call.func)
    if term in _DENY_TERMINAL:
        return dn or term
    return None


def rule_blocking_under_lock(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    out: List[Violation] = []

    def _scan_node(node: ast.AST) -> None:
        """Walk a statement inside a held-lock region, pruning nested
        function/lambda bodies (they run later, lock not necessarily
        held)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            hit = _deny_match(node)
            if hit and not ctx.allowed(node.lineno, "blocking"):
                out.append(Violation(
                    ctx.path, node.lineno, "R3", "blocking-under-lock",
                    f"blocking call `{hit}` inside a `with <lock>:` block — "
                    "serialize off-lock (docs/durability.md, "
                    "docs/tiered-storage.md) or annotate "
                    "`# pilint: allow-blocking(reason)`",
                ))
        for child in ast.iter_child_nodes(node):
            _scan_node(child)

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.With) and any(
                _is_lock_name(item.context_expr) for item in node.items):
            for stmt in node.body:
                _scan_node(stmt)
            # nested withs inside are re-visited below, which is fine:
            # the outer scan already reported their bodies' direct calls,
            # and allowed() marks by line so duplicates collapse.
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(ctx.tree)
    # de-duplicate (nested lock-withs make the outer and inner visit both
    # report the same call)
    seen: Set[tuple] = set()
    unique = []
    for v in out:
        k = (v.line, v.message)
        if k not in seen:
            seen.add(k)
            unique.append(v)
    return unique


# --------------------------------------------------------------------------
# R4: counter hygiene


def _is_self_counters(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "counters"
            and terminal_name(node.value) == "self")


def _class_has_wholesale_snapshot(cls: ast.ClassDef) -> bool:
    # A snapshot() only counts as wholesale when it exports the WHOLE
    # counter dict — `dict(self.counters)`, `self.counters.copy()`,
    # `{**self.counters, ...}`, or `return self.counters` — not merely any
    # mention of self.counters. A partial export (`self.counters['hits']`)
    # must NOT grant the whole class R4 immunity.
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "snapshot":
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "dict"
                        and any(_is_self_counters(a) for a in sub.args)):
                    return True
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "copy"
                        and _is_self_counters(sub.func.value)):
                    return True
                if isinstance(sub, ast.Dict) and any(
                        k is None and _is_self_counters(v)
                        for k, v in zip(sub.keys, sub.values)):
                    return True
                if isinstance(sub, ast.Return) and _is_self_counters(sub.value):
                    return True
    return False


def rule_counter_hygiene(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    if not ctx.path.startswith("pilosa_tpu/"):
        return []
    out: List[Violation] = []

    def scan(body, wholesale: bool) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                scan(node.body, _class_has_wholesale_snapshot(node))
                continue
            # BFS that PRUNES nested ClassDefs (classes inside functions):
            # each is re-dispatched through scan() so its increments are
            # judged against its OWN snapshot(), not the enclosing class's.
            todo = [node]
            while todo:
                sub = todo.pop(0)
                if isinstance(sub, ast.ClassDef):
                    scan(sub.body, _class_has_wholesale_snapshot(sub))
                    continue
                todo.extend(ast.iter_child_nodes(sub))
                # counters["key"] += n
                if (isinstance(sub, ast.AugAssign)
                        and isinstance(sub.target, ast.Subscript)
                        and terminal_name(sub.target.value) == "counters"):
                    sl = sub.target.slice
                    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                        key = sl.value
                        if (not wholesale
                                and key not in env.wired_literals
                                and not ctx.allowed(sub.lineno, "counter")):
                            out.append(Violation(
                                ctx.path, sub.lineno, "R4", "counter-hygiene",
                                f"counter {key!r} is incremented but not "
                                "reachable from /debug/vars: export it via a "
                                "wholesale snapshot() or wire the literal in "
                                "handler.py/diagnostics.py",
                            ))
                # stats.count("Name", n)
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "count" and sub.args):
                    a0 = sub.args[0]
                    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                        name = a0.value
                        if (not env.stats_wholesale
                                and name not in env.wired_literals
                                and not ctx.allowed(sub.lineno, "counter")):
                            out.append(Violation(
                                ctx.path, sub.lineno, "R4", "counter-hygiene",
                                f"stats counter {name!r} is not surfaced: "
                                "/debug/vars no longer dumps stats.snapshot() "
                                "wholesale and the name appears nowhere in "
                                "the wiring files",
                            ))

    scan(ctx.tree.body, False)
    return out


# --------------------------------------------------------------------------
# R6: failpoint hygiene


# Must track pilosa_tpu/failpoints.py's _SPEC_RE action set: a string is
# only treated as an activation spec when its right-hand side parses as a
# real action, so ordinary "key=value" literals never false-positive.
_FP_NAME = r"[a-z][a-z0-9_.-]*"
_FP_SPEC_PART_RE = re.compile(
    rf"^(?P<name>{_FP_NAME})(?:@[^=;\s]+)?="
    r"(?:\d+\*)?(?:error|crash|drop|oom|latency|flaky)(?:\([^)]*\))?$"
)
_FP_NAME_RE = re.compile(rf"^{_FP_NAME}(?:@.+)?$")


def parse_failpoint_docs(text: str) -> Set[str]:
    """Failpoint names from the reference table in docs/durability.md:
    table rows (lines starting with `|`) inside the `## Failpoints`
    section whose first cell is a backticked name."""
    names: Set[str] = set()
    in_section = False
    for line in text.splitlines():
        if line.startswith("## "):
            in_section = "failpoint" in line.lower()
            continue
        if in_section:
            m = re.match(rf"\|\s*`({_FP_NAME})`", line)
            if m:
                names.add(m.group(1))
    return names


def collect_fire_names(tree: ast.AST) -> Set[str]:
    """Every string literal passed as the first arg of a fire() call."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) == "fire" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.add(node.args[0].value)
    return out


def collect_spec_sites(path: str, source: str) -> List:
    """(path, line, base-name) for every failpoint a test activates:
    string literals that parse as `name[@target]=action` specs (activate()
    / PILOSA_TPU_FAILPOINTS values) plus plain-string first args of
    configure(). Lines carrying `# pilint: allow-failpoint(reason)` are
    excluded — registry/grammar tests use deliberately-bogus names."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    annotations, _ = parse_annotations(path, source)
    ctx = FileContext(path=path, source=source, tree=tree,
                      annotations=annotations)
    out: List = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for part in node.value.split(";"):
                m = _FP_SPEC_PART_RE.match(part.strip())
                if m and not ctx.allowed(node.lineno, "failpoint"):
                    out.append((path, node.lineno, m.group("name")))
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) == "configure" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            name = node.args[0].value
            if (_FP_NAME_RE.match(name)
                    and not ctx.allowed(node.lineno, "failpoint")):
                out.append((path, node.lineno, name.split("@")[0]))
    return out


def rule_failpoint_hygiene(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R6a: every fire("<name>") site in pilosa_tpu/ must appear in the
    docs/durability.md reference table — the table is how tests and
    operators discover injection points, and an undocumented point is
    one nobody will ever activate."""
    if not ctx.path.startswith("pilosa_tpu/") or not env.failpoint_docs_loaded:
        return []
    out: List[Violation] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and terminal_name(node.func) == "fire" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        if name in env.failpoint_doc_names:
            continue
        if ctx.allowed(node.lineno, "failpoint"):
            continue
        out.append(Violation(
            ctx.path, node.lineno, "R6", "failpoint-hygiene",
            f"failpoint {name!r} fires here but is missing from the "
            f"reference table in {FAILPOINT_DOC} — add a table row or "
            "annotate `# pilint: allow-failpoint(reason)`",
        ))
    return out


def failpoint_orphan_violations(env: RepoEnv) -> List[Violation]:
    """R6b (repo-level, emitted by the runner after per-file rules): every
    failpoint name a test activates must have a fire() site — a typo'd
    spec never fires, silently turning a fault test into a no-op."""
    out: List[Violation] = []
    for path, line, name in env.failpoint_spec_sites:
        if name not in env.failpoint_fire_sites:
            out.append(Violation(
                path, line, "R6", "failpoint-hygiene",
                f"activation spec names failpoint {name!r} but no "
                "failpoints.fire() site carries that name — the spec "
                "never fires and this fault test is a no-op; fix the "
                "name or annotate `# pilint: allow-failpoint(reason)`",
            ))
    return out


# --------------------------------------------------------------------------
# R7: span-name hygiene


# The recorder surface (pilosa_tpu/obs/): obs.span()/obs_span() open a
# stage span, obs.record()/obs_record()/trace.record() append a
# pre-measured one. Only CONSTANT first-arg names are checked — dynamic
# names (the f-string `remote:<peer>` hops) can't be validated statically
# and are documented in the table for humans, not the linter.
_SPAN_CALL_FUNCS = {"span", "obs_span", "record", "obs_record"}
# Test-side assertion helpers whose span-name argument R7b validates:
# a trace-shaped assertion naming a span nothing records is a no-op test.
_SPAN_ASSERT_FUNCS = {"assert_span", "find_span", "find_spans"}
_SPAN_NAME = r"[a-z][a-z0-9_.:<>-]*"


def parse_span_docs(text: str) -> Set[str]:
    """Span names from the reference table in docs/observability.md:
    table rows (lines starting with `|`) inside a `## ... span ...`
    section whose first cell is a backticked name."""
    names: Set[str] = set()
    in_section = False
    for line in text.splitlines():
        if line.startswith("## "):
            in_section = "span" in line.lower()
            continue
        if in_section:
            m = re.match(rf"\|\s*`({_SPAN_NAME})`", line)
            if m:
                names.add(m.group(1))
    return names


def _span_call_name(node: ast.Call):
    """The constant span name of a recorder call, or None."""
    if (terminal_name(node.func) in _SPAN_CALL_FUNCS and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)):
        return node.args[0].value
    return None


def collect_span_names(tree: ast.AST) -> Set[str]:
    """Every constant span name recorded anywhere in a module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _span_call_name(node)
            if name is not None:
                out.add(name)
    return out


def collect_span_assert_sites(path: str, source: str) -> List:
    """(path, line, name) for every span name a test asserts on: constant
    string args of assert_span()/find_span() helper calls. Lines carrying
    `# pilint: allow-span(reason)` are excluded — fixture negatives use
    deliberately-bogus names."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    annotations, _ = parse_annotations(path, source)
    ctx = FileContext(path=path, source=source, tree=tree,
                      annotations=annotations)
    out: List = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) in _SPAN_ASSERT_FUNCS):
            for arg in node.args:
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and re.fullmatch(_SPAN_NAME, arg.value)
                        and not ctx.allowed(node.lineno, "span")):
                    out.append((path, node.lineno, arg.value))
    return out


def rule_span_hygiene(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    """R7a: every constant span name passed to the recorder in
    pilosa_tpu/ must appear in docs/observability.md's span reference
    table — the table is how operators (and the trace-shaped tests)
    discover stage names, and an undocumented span is one nobody will
    filter or alert on."""
    if not ctx.path.startswith("pilosa_tpu/") or not env.span_docs_loaded:
        return []
    out: List[Violation] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _span_call_name(node)
        if name is None or name in env.span_doc_names:
            continue
        if ctx.allowed(node.lineno, "span"):
            continue
        out.append(Violation(
            ctx.path, node.lineno, "R7", "span-hygiene",
            f"span {name!r} is recorded here but missing from the span "
            f"reference table in {SPAN_DOC} — add a table row or annotate "
            "`# pilint: allow-span(reason)`",
        ))
    return out


def span_orphan_violations(env: RepoEnv) -> List[Violation]:
    """R7b (repo-level, emitted by the runner after per-file rules): every
    span name a test asserts on must have a recording site — a typo'd
    assertion waits on a span that never records, silently turning a
    trace-shaped test into a no-op."""
    out: List[Violation] = []
    for path, line, name in env.span_assert_sites:
        if name not in env.span_record_sites:
            out.append(Violation(
                path, line, "R7", "span-hygiene",
                f"test asserts on span {name!r} but no recording site "
                "carries that name — the assertion can never match; fix "
                "the name or annotate `# pilint: allow-span(reason)`",
            ))
    return out


# --------------------------------------------------------------------------
# R5: mutation-epoch audit (core/ only)


_STORAGE_MUTATORS = {"add", "remove", "add_many", "remove_many",
                     "add_sorted", "remove_sorted", "read_from"}
_BUMP_CALLS = {"bump", "_invalidate_row", "_invalidate_bulk", "_journal_reset"}


def _method_facts(fn: ast.FunctionDef):
    """(mutates: [lineno], bumps: bool, callees: set[str]) for one method."""
    mutates: List[int] = []
    bumps = False
    callees: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                base = terminal_name(f.value)
                if f.attr in _STORAGE_MUTATORS and base == "storage":
                    mutates.append(node.lineno)
                if f.attr in _BUMP_CALLS:
                    bumps = True
                if isinstance(f.value, ast.Name) and f.value.id == "self":
                    callees.add(f.attr)
            elif isinstance(f, ast.Name):
                if f.id == "replay_ops":
                    mutates.append(node.lineno)
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == "generation":
                    bumps = True
    return mutates, bumps, callees


def rule_mutation_epoch(ctx: FileContext, env: RepoEnv) -> List[Violation]:
    if "core/" not in ctx.path:
        return []
    out: List[Violation] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {m.name: m for m in node.body
                   if isinstance(m, ast.FunctionDef)}
        facts = {name: _method_facts(fn) for name, fn in methods.items()}

        def reaches_bump(name: str, seen: Set[str]) -> bool:
            if name in seen or name not in facts:
                return False
            seen.add(name)
            _, bumps, callees = facts[name]
            if bumps:
                return True
            return any(reaches_bump(c, seen) for c in callees)

        for name, fn in methods.items():
            mutates, _, _ = facts[name]
            if not mutates:
                continue
            if reaches_bump(name, set()):
                continue
            if ctx.allowed(fn.lineno, "mutation"):
                continue
            out.append(Violation(
                ctx.path, fn.lineno, "R5", "mutation-epoch-audit",
                f"`{name}` mutates bitmap storage (line {mutates[0]}) but "
                "never reaches a generation/epoch bump — stale device "
                "caches would serve the old plane; bump or annotate "
                "`# pilint: allow-mutation(reason)`",
            ))
    return out


ALL_RULES = (
    ("R1", rule_swallow),
    ("R2", rule_jax_free),
    ("R3", rule_blocking_under_lock),
    ("R4", rule_counter_hygiene),
    ("R5", rule_mutation_epoch),
    ("R6", rule_failpoint_hygiene),
    ("R7", rule_span_hygiene),
)
