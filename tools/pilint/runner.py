"""File discovery + rule orchestration + report formatting.

v2 perf model: every file under the repo's lint corpus is read and
parsed EXACTLY once into a shared cache — the per-file rules, the R6/R7
cross-file corpora, and the R11 config surface all consume the same
trees (the v1 runner re-read and re-parsed the tree up to three times).
`--changed <ref>` lints only files `git diff --name-only <ref>` reports
(plus untracked ones), with the cross-file corpora still gathered from
the full tree so repo-level rules stay sound on a partial target set.
"""

from __future__ import annotations

import ast
import os
import subprocess
from typing import Dict, Iterable, List, Optional, Tuple

from .core import (FileContext, Violation, dotted_name, parse_annotations,
                   unused_annotation_violations)
from .rules import (ALL_RULES, CLI_FILE, CONFIG_FILE, FAILPOINT_DOC, R11_SECTIONS,
                    RepoEnv, SPAN_DOC, WIRING_FILES, build_env,
                    collect_fire_names, collect_span_assert_sites,
                    collect_span_names, collect_spec_sites,
                    collect_string_constants, failpoint_orphan_violations,
                    parse_failpoint_docs, parse_span_docs,
                    span_orphan_violations)

_SKIP_PARTS = {"__pycache__", ".git"}


def _discover(paths: Iterable[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            files.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_PARTS)
            for n in sorted(names):
                if n.endswith(".py"):
                    files.append(os.path.join(root, n))
    return files


def _relpath(path: str, repo_root: Optional[str]) -> str:
    root = repo_root or os.getcwd()
    try:
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    except ValueError:  # different drive (windows): keep as-is
        rel = path
    return rel.replace(os.sep, "/")


class SourceCache:
    """rel-path -> (source, tree-or-None): each file is read and parsed
    once per run, shared by per-file rules and every cross-file corpus."""

    def __init__(self, root: str):
        self.root = root
        self._entries: Dict[str, Tuple[str, Optional[ast.AST]]] = {}

    def get(self, rel: str) -> Optional[Tuple[str, Optional[ast.AST]]]:
        if rel in self._entries:
            return self._entries[rel]
        full = os.path.join(self.root, rel)
        try:
            with open(full, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            return None
        try:
            tree: Optional[ast.AST] = ast.parse(source)
        except SyntaxError:
            tree = None
        self._entries[rel] = (source, tree)
        return self._entries[rel]

    def tree(self, rel: str) -> Optional[ast.AST]:
        entry = self.get(rel)
        return entry[1] if entry else None

    def source(self, rel: str) -> Optional[str]:
        entry = self.get(rel)
        return entry[0] if entry else None


def lint_file(path: str, env: RepoEnv, repo_root: Optional[str] = None,
              rules: Optional[Iterable[str]] = None, depth: int = 0,
              cache: Optional[SourceCache] = None) -> List[Violation]:
    rel = _relpath(path, repo_root)
    if cache is not None:
        entry = cache.get(rel)
        if entry is not None:
            return lint_source(rel, entry[0], env, rules=rules, depth=depth,
                               tree=entry[1])
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    return lint_source(rel, source, env, rules=rules, depth=depth)


def lint_source(rel_path: str, source: str, env: RepoEnv,
                rules: Optional[Iterable[str]] = None, depth: int = 0,
                tree: Optional[ast.AST] = None) -> List[Violation]:
    """Lint one in-memory module (the fixture-snippet path for tests).
    `tree` lets the runner hand over the already-parsed AST."""
    if tree is None:
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            return [Violation(rel_path, e.lineno or 0, "E0", "syntax-error",
                              str(e.msg))]
    annotations, violations = parse_annotations(rel_path, source)
    ctx = FileContext(path=rel_path, source=source, tree=tree,
                      annotations=annotations, depth=depth)
    selected = set(rules) if rules else None
    for rule_id, rule_fn in ALL_RULES:
        if selected and rule_id not in selected:
            continue
        violations.extend(rule_fn(ctx, env))
    # Only meaningful when every rule ran — a partial run would call
    # legitimately-needed annotations unused.
    if selected is None:
        violations.extend(unused_annotation_violations(ctx))
    return sorted(violations, key=Violation.sort_key)


def _pilosa_files(cache: SourceCache) -> List[str]:
    return [_relpath(f, cache.root)
            for f in _discover([os.path.join(cache.root, "pilosa_tpu")])]


def _load_failpoint_env(env: RepoEnv, cache: SourceCache) -> None:
    """R6's cross-file corpus, gathered independently of the lint target
    set so `pilint pilosa_tpu/` still validates test specs: the docs
    reference table, every fire() site under pilosa_tpu/, and every
    activation spec under tests/."""
    doc = os.path.join(cache.root, FAILPOINT_DOC)
    if os.path.exists(doc):
        with open(doc, "r", encoding="utf-8") as f:
            env.failpoint_doc_names = parse_failpoint_docs(f.read())
        env.failpoint_docs_loaded = True
    for rel in _pilosa_files(cache):
        tree = cache.tree(rel)
        if tree is not None:
            env.failpoint_fire_sites |= collect_fire_names(tree)
    tests_dir = os.path.join(cache.root, "tests")
    if os.path.isdir(tests_dir):
        for f in _discover([tests_dir]):
            rel = _relpath(f, cache.root)
            src = cache.source(rel)
            if src is not None:
                env.failpoint_spec_sites.extend(collect_spec_sites(rel, src))


def _load_span_env(env: RepoEnv, cache: SourceCache) -> None:
    """R7's cross-file corpus, mirroring R6's: the span reference table
    in docs/observability.md, every constant recorder span name under
    pilosa_tpu/, and every span name tests assert on under tests/."""
    doc = os.path.join(cache.root, SPAN_DOC)
    if os.path.exists(doc):
        with open(doc, "r", encoding="utf-8") as f:
            env.span_doc_names = parse_span_docs(f.read())
        env.span_docs_loaded = True
    for rel in _pilosa_files(cache):
        tree = cache.tree(rel)
        if tree is not None:
            env.span_record_sites |= collect_span_names(tree)
    tests_dir = os.path.join(cache.root, "tests")
    if os.path.isdir(tests_dir):
        for f in _discover([tests_dir]):
            rel = _relpath(f, cache.root)
            src = cache.source(rel)
            if src is not None:
                env.span_assert_sites.extend(
                    collect_span_assert_sites(rel, src))


def _load_config_env(env: RepoEnv, cache: SourceCache) -> None:
    """R11's corpus: string constants of config.py (env spellings,
    flag-mapping keys) and cli.py (flag spellings), the section-scoped
    parse surface (every dotted `self.<section>.<field>` store) and
    to_toml dump rows (row constants bucketed by their `[section]`
    header, in source order — a key two sections share must not mask
    either one's drift), plus each section's reference doc text."""
    import re as _re

    cfg_tree = cache.tree(CONFIG_FILE)
    cli_tree = cache.tree(CLI_FILE)
    if cfg_tree is None or cli_tree is None:
        return  # not this repo's layout (fixture run): rule stays off
    env.config_constants = collect_string_constants(cfg_tree)
    env.cli_constants = collect_string_constants(cli_tree)
    for node in ast.walk(cfg_tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        for t in targets:
            dn = dotted_name(t)
            if dn is not None:
                env.config_set_attrs.add(dn)
    for node in ast.walk(cfg_tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == "to_toml"):
            consts = sorted(
                (c.lineno, c.col_offset, c.value) for c in ast.walk(node)
                if isinstance(c, ast.Constant) and isinstance(c.value, str))
            current = "_top"
            for _ln, _col, value in consts:
                m = _re.fullmatch(r"\[([a-z][a-z-]*)\]", value)
                if m:
                    current = m.group(1).replace("-", "_")
                    continue
                env.config_dump_rows.setdefault(current, set()).add(value)
    for _cls, (_section, _flag, _env, doc_path) in R11_SECTIONS.items():
        full = os.path.join(cache.root, doc_path)
        if doc_path not in env.config_docs and os.path.exists(full):
            with open(full, "r", encoding="utf-8") as f:
                env.config_docs[doc_path] = f.read()
    env.config_surface_loaded = True


def changed_files(ref: str, root: str) -> List[str]:
    """Lint targets for --changed: `git diff --name-only <ref>` plus
    untracked files, filtered to .py paths that still exist AND sit in
    the lint corpus (pilosa_tpu/) — the full-tree run lints exactly
    that corpus, and test files deliberately violate rules on purpose
    (fixture snippets), so a changed test must not fail the gate."""
    out: List[str] = []
    for args in (["git", "diff", "--name-only", ref],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(args, cwd=root, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(args)} failed: {proc.stderr.strip()}")
        for line in proc.stdout.splitlines():
            line = line.strip()
            if (line.endswith(".py") and line.startswith("pilosa_tpu/")
                    and os.path.exists(os.path.join(root, line))):
                out.append(os.path.join(root, line))
    return sorted(set(out))


def lint_paths(paths: Iterable[str], repo_root: Optional[str] = None,
               rules: Optional[Iterable[str]] = None,
               depth: int = 0) -> List[Violation]:
    """Lint every .py file under `paths`. repo_root anchors the relative
    paths rules match on (zone membership, wiring files); default cwd.
    `depth` bounds the interprocedural walks (0 = DEFAULT_DEPTH)."""
    files = _discover(paths)
    root = repo_root or os.getcwd()
    cache = SourceCache(root)
    sources: Dict[str, str] = {}
    for rel in WIRING_FILES:
        src = cache.source(rel)
        if src is not None:
            sources[rel] = src
    env = build_env(sources)
    selected = set(rules) if rules else None
    if selected is None or "R6" in selected:
        _load_failpoint_env(env, cache)
    if selected is None or "R7" in selected:
        _load_span_env(env, cache)
    if selected is None or "R11" in selected:
        _load_config_env(env, cache)
    out: List[Violation] = []
    for f in files:
        out.extend(lint_file(f, env, repo_root=root, rules=rules,
                             depth=depth, cache=cache))
    if selected is None or "R6" in selected:
        out.extend(failpoint_orphan_violations(env))
    if selected is None or "R7" in selected:
        out.extend(span_orphan_violations(env))
    return sorted(out, key=Violation.sort_key)


def format_report(violations: List[Violation]) -> str:
    lines = [str(v) for v in violations]
    n = len(violations)
    lines.append(f"pilint: {n} violation{'s' if n != 1 else ''}")
    return "\n".join(lines)
