"""File discovery + rule orchestration + report formatting."""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Tuple

from .core import (FileContext, Violation, parse_annotations,
                   unused_annotation_violations)
from .rules import (ALL_RULES, FAILPOINT_DOC, RepoEnv, SPAN_DOC, WIRING_FILES,
                    build_env, collect_fire_names, collect_span_assert_sites,
                    collect_span_names, collect_spec_sites,
                    failpoint_orphan_violations, parse_failpoint_docs,
                    parse_span_docs, span_orphan_violations)

_SKIP_PARTS = {"__pycache__", ".git"}


def _discover(paths: Iterable[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            files.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_PARTS)
            for n in sorted(names):
                if n.endswith(".py"):
                    files.append(os.path.join(root, n))
    return files


def _relpath(path: str, repo_root: Optional[str]) -> str:
    root = repo_root or os.getcwd()
    try:
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    except ValueError:  # different drive (windows): keep as-is
        rel = path
    return rel.replace(os.sep, "/")


def lint_file(path: str, env: RepoEnv, repo_root: Optional[str] = None,
              rules: Optional[Iterable[str]] = None) -> List[Violation]:
    rel = _relpath(path, repo_root)
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    return lint_source(rel, source, env, rules=rules)


def lint_source(rel_path: str, source: str, env: RepoEnv,
                rules: Optional[Iterable[str]] = None) -> List[Violation]:
    """Lint one in-memory module (the fixture-snippet path for tests)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation(rel_path, e.lineno or 0, "E0", "syntax-error",
                          str(e.msg))]
    annotations, violations = parse_annotations(rel_path, source)
    ctx = FileContext(path=rel_path, source=source, tree=tree,
                      annotations=annotations)
    selected = set(rules) if rules else None
    for rule_id, rule_fn in ALL_RULES:
        if selected and rule_id not in selected:
            continue
        violations.extend(rule_fn(ctx, env))
    # Only meaningful when every rule ran — a partial run would call
    # legitimately-needed annotations unused.
    if selected is None:
        violations.extend(unused_annotation_violations(ctx))
    return sorted(violations, key=Violation.sort_key)


def _load_failpoint_env(env: RepoEnv, root: str) -> None:
    """R6's cross-file corpus, gathered independently of the lint target
    set so `pilint pilosa_tpu/` still validates test specs: the docs
    reference table, every fire() site under pilosa_tpu/, and every
    activation spec under tests/."""
    import ast as _ast

    doc = os.path.join(root, FAILPOINT_DOC)
    if os.path.exists(doc):
        with open(doc, "r", encoding="utf-8") as f:
            env.failpoint_doc_names = parse_failpoint_docs(f.read())
        env.failpoint_docs_loaded = True
    for f in _discover([os.path.join(root, "pilosa_tpu")]):
        try:
            with open(f, "r", encoding="utf-8") as fh:
                env.failpoint_fire_sites |= collect_fire_names(
                    _ast.parse(fh.read()))
        except (OSError, SyntaxError):
            continue  # unreadable/unparseable files get their own E0
    tests_dir = os.path.join(root, "tests")
    if os.path.isdir(tests_dir):
        for f in _discover([tests_dir]):
            try:
                with open(f, "r", encoding="utf-8") as fh:
                    src = fh.read()
            except OSError:
                continue
            env.failpoint_spec_sites.extend(
                collect_spec_sites(_relpath(f, root), src))


def _load_span_env(env: RepoEnv, root: str) -> None:
    """R7's cross-file corpus, mirroring R6's: the span reference table
    in docs/observability.md, every constant recorder span name under
    pilosa_tpu/, and every span name tests assert on under tests/."""
    import ast as _ast

    doc = os.path.join(root, SPAN_DOC)
    if os.path.exists(doc):
        with open(doc, "r", encoding="utf-8") as f:
            env.span_doc_names = parse_span_docs(f.read())
        env.span_docs_loaded = True
    for f in _discover([os.path.join(root, "pilosa_tpu")]):
        try:
            with open(f, "r", encoding="utf-8") as fh:
                env.span_record_sites |= collect_span_names(
                    _ast.parse(fh.read()))
        except (OSError, SyntaxError):
            continue  # unreadable/unparseable files get their own E0
    tests_dir = os.path.join(root, "tests")
    if os.path.isdir(tests_dir):
        for f in _discover([tests_dir]):
            try:
                with open(f, "r", encoding="utf-8") as fh:
                    src = fh.read()
            except OSError:
                continue
            env.span_assert_sites.extend(
                collect_span_assert_sites(_relpath(f, root), src))


def lint_paths(paths: Iterable[str], repo_root: Optional[str] = None,
               rules: Optional[Iterable[str]] = None) -> List[Violation]:
    """Lint every .py file under `paths`. repo_root anchors the relative
    paths rules match on (zone membership, wiring files); default cwd."""
    files = _discover(paths)
    root = repo_root or os.getcwd()
    sources: Dict[str, str] = {}
    for rel in WIRING_FILES:
        full = os.path.join(root, rel)
        if os.path.exists(full):
            with open(full, "r", encoding="utf-8") as f:
                sources[rel] = f.read()
    env = build_env(sources)
    selected = set(rules) if rules else None
    if selected is None or "R6" in selected:
        _load_failpoint_env(env, root)
    if selected is None or "R7" in selected:
        _load_span_env(env, root)
    out: List[Violation] = []
    for f in files:
        out.extend(lint_file(f, env, repo_root=root, rules=rules))
    if selected is None or "R6" in selected:
        out.extend(failpoint_orphan_violations(env))
    if selected is None or "R7" in selected:
        out.extend(span_orphan_violations(env))
    return sorted(out, key=Violation.sort_key)


def format_report(violations: List[Violation]) -> str:
    lines = [str(v) for v in violations]
    n = len(violations)
    lines.append(f"pilint: {n} violation{'s' if n != 1 else ''}")
    return "\n".join(lines)
