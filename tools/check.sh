#!/usr/bin/env bash
# Single-entry gate: the three checks a change must pass, in cost order,
# fail-fast. Run from the repo root:
#
#   tools/check.sh            # pilint full tree -> tier-1 pytest -> bench smoke
#   tools/check.sh --changed  # pilint incremental (vs HEAD) first instead
#
# Each stage's exit code stops the gate; the summary line at the end is
# what CI (and a builder's eyeball) keys on.
set -u -o pipefail

cd "$(dirname "$0")/.."

MODE="full"
if [ "${1:-}" = "--changed" ]; then
    MODE="changed"
fi

stage() {
    echo "==> $1"
}

fail() {
    echo "check.sh: FAIL at $1"
    exit 1
}

stage "pilint ($MODE tree)"
if [ "$MODE" = "changed" ]; then
    python -m tools.pilint --changed HEAD || fail "pilint"
else
    python -m tools.pilint pilosa_tpu/ || fail "pilint"
fi

stage "tier-1 pytest (-m 'not slow')"
# CHECK_TOLERATE_KNOWN=1 accepts pytest rc 1 ("some tests failed") for
# environments carrying the documented jax multi-process API gap (two
# two-process tests; see ROADMAP "compare DOTS_PASSED, not rc"). Any
# other exit (collection error, crash) still fails the gate.
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    -p no:cacheprovider
rc=$?
if [ "$rc" -ne 0 ]; then
    if [ "$rc" -eq 1 ] && [ "${CHECK_TOLERATE_KNOWN:-0}" = "1" ]; then
        echo "check.sh: WARNING tolerating pytest rc 1 (CHECK_TOLERATE_KNOWN=1)"
    else
        fail "pytest"
    fi
fi

stage "bench smoke (BENCH_SMOKE=1)"
BENCH_SMOKE=1 JAX_PLATFORMS=cpu python bench.py || fail "bench"

echo "check.sh: OK (pilint + tier-1 + bench smoke)"
