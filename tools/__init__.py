"""Developer tooling that ships with the repo but not the package."""
